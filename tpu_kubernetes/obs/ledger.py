"""Goodput accounting: every decoded token classified exactly once.

The serving stack deliberately throws work away — shed requests,
deadline-expired slots, mid-decode cancellations, slot-engine rows
decoding past their done mask — but until now nothing said how much of
the device's output was *useful*. This module is the ledger the serve
path feeds (PAPERS.md: the Gemma-on-Cloud-TPU comparison is framed
around MFU and tokens-per-dollar; Podracer makes utilization accounting
the organizing principle): a conservation law over decoded tokens,

    ``tpu_serve_tokens_emitted_total == sum(tpu_serve_tokens_total{class=*})``

held at quiescence for every serve path. Production is counted at the
production sites (a prefill's sampled token, a decode segment's
``steps x rows`` grid) and settlement at the terminal sites (delivery,
cancellation, expiry, fail-out), so a dropped settlement *breaks the
invariant* instead of silently flattering goodput — the chaos test in
tests/test_faults.py exists to catch exactly that.

Classes:

* ``useful``     — delivered to a client.
* ``cancelled``  — client disconnected / request cancelled mid-decode.
* ``expired``    — request deadline fired after tokens were decoded.
* ``shed-spent`` — prefill (or more) was spent, then the entry was
  failed out (engine reset, insert failure).
* ``bubble``     — decoded but never deliverable: slot-engine rows past
  their done mask, empty slots inside a segment, pad rows in a static
  batch, tokens beyond the requested budget, trailing EOS.
* ``speculative-waste`` — verify-window cells whose draft the target
  rejected: the slot engine decodes ``draft_k+1`` candidates per live
  row per verify round and keeps only the accepted prefix (+1
  correction); the rejected remainder is the price of speculation,
  kept distinct from ``bubble`` so acceptance-rate regressions show up
  in the ledger, not just the spec counters.

Device seconds ride the same classes (``tpu_serve_device_seconds_total``)
as best-effort attribution — tokens are the *tested* invariant.

The slot-engine timeline (:meth:`TokenLedger.segment`) additionally
records per-segment (live rows, occupied slots, admitted/drained/reaped)
so ``GET /debug/ledger`` can show intra-segment utilization, and keeps
the running ``tpu_serve_slot_bubble_fraction`` gauge — the fraction of
slot-engine row-steps that decoded nothing a client will see.

No jax import: the CLI renders remote ledgers without an accelerator
stack, and the serve server imports this before jax is up.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque

from tpu_kubernetes.obs.metrics import REGISTRY, Registry

USEFUL = "useful"
CANCELLED = "cancelled"
EXPIRED = "expired"
SHED_SPENT = "shed-spent"
BUBBLE = "bubble"
SPECULATIVE_WASTE = "speculative-waste"
CLASSES = (USEFUL, CANCELLED, EXPIRED, SHED_SPENT, BUBBLE,
           SPECULATIVE_WASTE)

TIMELINE_MAX = 512


class TokenLedger:
    """Thread-safe token/device-second ledger + slot-engine timeline.

    All mutators clamp to non-negative and never raise on bad input —
    accounting must not take the serving path down. ``reset()`` zeroes
    the internal view and re-binds the metric families (so tests that
    ``REGISTRY.reset()`` get fresh counters; without a registry reset
    the exposition counters stay monotone, as Prometheus requires).
    """

    def __init__(self, registry: Registry | None = None, *,
                 timeline_max: int = TIMELINE_MAX):
        self._registry = registry if registry is not None else REGISTRY
        self._lock = threading.Lock()
        self._timeline_max = timeline_max
        self._zero()
        self._bind()

    def _zero(self) -> None:
        self._emitted = 0
        self._tokens = {c: 0 for c in CLASSES}
        self._seconds = {c: 0.0 for c in CLASSES}
        self._row_steps = 0
        self._live_steps = 0
        self._segments = 0
        self._timeline: deque[dict] = deque(maxlen=self._timeline_max)

    def _bind(self) -> None:
        r = self._registry
        self._tok_fam = r.counter(
            "tpu_serve_tokens_total",
            "decoded tokens by settlement class (useful / cancelled / "
            "expired / shed-spent / bubble); classes sum to "
            "tpu_serve_tokens_emitted_total at quiescence",
            labelnames=("class",),
        )
        self._emit_fam = r.counter(
            "tpu_serve_tokens_emitted_total",
            "decoded tokens produced by the device (the production side "
            "of the goodput conservation law; warm-up excluded)",
        )
        self._sec_fam = r.counter(
            "tpu_serve_device_seconds_total",
            "device seconds attributed by settlement class (best-effort "
            "apportioning; tokens are the conserved quantity)",
            labelnames=("class",),
        )
        self._bubble_gauge = r.gauge(
            "tpu_serve_slot_bubble_fraction",
            "continuous batching: fraction of slot-engine row-steps that "
            "decoded nothing deliverable (empty slots and done rows "
            "inside segments) — cumulative over all segments",
        )
        # pre-create every class child so the full family renders from
        # the first scrape, samples or not (the registry-wide idiom)
        for c in CLASSES:
            self._tok_fam.labels(c)
            self._sec_fam.labels(c)

    # -- production --------------------------------------------------------

    def emitted(self, n: int) -> None:
        """Count ``n`` tokens the device just produced. Called at the
        production sites (prefill sample, decode segment grids) —
        BEFORE anyone decides what the tokens were for."""
        n = int(n)
        if n <= 0:
            return
        with self._lock:
            self._emitted += n
        self._emit_fam.inc(n)

    # -- settlement --------------------------------------------------------

    def settle(self, cls: str, tokens: int = 0,
               device_s: float = 0.0) -> None:
        """Classify ``tokens`` produced earlier (and optionally device
        seconds) under ``cls``. Zero amounts are no-ops."""
        if cls not in self._tokens:
            raise ValueError(f"unknown ledger class {cls!r} "
                             f"(one of {list(CLASSES)})")
        tokens = max(0, int(tokens))
        device_s = max(0.0, float(device_s))
        if tokens:
            with self._lock:
                self._tokens[cls] += tokens
            self._tok_fam.labels(cls).inc(tokens)
        if device_s:
            with self._lock:
                self._seconds[cls] += device_s
            self._sec_fam.labels(cls).inc(device_s)

    def settle_request(self, cls: str, *, delivered: int, decoded: int,
                       device_s: float = 0.0) -> None:
        """One finished request: ``delivered`` tokens under ``cls``, the
        rest of its ``decoded`` raw tokens (budget trim, trailing EOS)
        as bubble."""
        delivered = max(0, int(delivered))
        decoded = max(delivered, int(decoded))
        self.settle(cls, delivered, device_s)
        self.settle(BUBBLE, decoded - delivered)

    def bubble(self, tokens: int, device_s: float = 0.0) -> None:
        self.settle(BUBBLE, tokens, device_s)

    # -- slot-engine timeline ----------------------------------------------

    def segment(self, *, steps: int, slots: int, occupied: int,
                live_steps: int, admitted: int = 0, drained: int = 0,
                reaped: int = 0, seconds: float = 0.0) -> None:
        """Record one slot-engine segment: the device ran
        ``steps x slots`` row-steps, of which ``live_steps`` advanced a
        resident request. Feeds the timeline and the cumulative
        ``tpu_serve_slot_bubble_fraction`` gauge."""
        row_steps = max(0, int(steps)) * max(0, int(slots))
        live_steps = min(max(0, int(live_steps)), row_steps)
        with self._lock:
            self._row_steps += row_steps
            self._live_steps += live_steps
            self._segments += 1
            frac = (1.0 - self._live_steps / self._row_steps
                    if self._row_steps else 0.0)
            self._timeline.append({
                "ts": round(time.time(), 3),
                "steps": int(steps), "slots": int(slots),
                "occupied": int(occupied), "live_steps": live_steps,
                "admitted": int(admitted), "drained": int(drained),
                "reaped": int(reaped),
                "seconds": round(float(seconds), 6),
            })
        self._bubble_gauge.set(round(frac, 6))

    # -- queries -----------------------------------------------------------

    def goodput(self) -> float | None:
        """useful / emitted over the ledger's lifetime, ``None`` before
        any production."""
        with self._lock:
            if not self._emitted:
                return None
            return self._tokens[USEFUL] / self._emitted

    def bubble_fraction(self) -> float | None:
        """Slot-engine row-step bubble fraction, ``None`` before any
        segment ran."""
        with self._lock:
            if not self._row_steps:
                return None
            return 1.0 - self._live_steps / self._row_steps

    def unsettled(self) -> int:
        """Produced-but-unclassified tokens: nonzero only while requests
        are in flight (or when a settlement site has a bug)."""
        with self._lock:
            return self._emitted - sum(self._tokens.values())

    def snapshot(self, timeline: int = 32) -> dict:
        """The ``GET /debug/ledger`` payload (roofline is merged in by
        the server from the profiler)."""
        with self._lock:
            classes = dict(self._tokens)
            seconds = {c: round(v, 6) for c, v in self._seconds.items()}
            emitted = self._emitted
            row_steps, live_steps = self._row_steps, self._live_steps
            segments = self._segments
            tail = list(self._timeline)[-max(0, timeline):]
        gp = classes[USEFUL] / emitted if emitted else None
        bf = 1.0 - live_steps / row_steps if row_steps else None
        return {
            "classes": classes,
            "emitted": emitted,
            "unsettled": emitted - sum(classes.values()),
            "goodput": round(gp, 6) if gp is not None else None,
            "device_seconds": seconds,
            "slot_engine": {
                "segments": segments,
                "row_steps": row_steps,
                "live_steps": live_steps,
                "bubble_fraction": (round(bf, 6)
                                    if bf is not None else None),
            },
            "timeline": tail,
        }

    def reset(self) -> None:
        """Zero the internal view and re-bind families (tests)."""
        with self._lock:
            self._zero()
        self._bind()


# the process-wide ledger the serve server feeds; `get goodput` and the
# chaos conservation test both read it through /debug/ledger
LEDGER = TokenLedger()


def render_ledger(payload: dict) -> str:
    """The ``tpu-kubernetes get goodput`` table for a /debug/ledger
    payload."""
    classes = payload.get("classes") or {}
    seconds = payload.get("device_seconds") or {}
    emitted = payload.get("emitted") or 0
    lines = [f"{'CLASS':<12} {'TOKENS':>10} {'SHARE':>8} {'DEVICE_S':>10}"]
    for cls in CLASSES:
        if cls not in classes:
            continue
        n = classes[cls]
        share = f"{n / emitted:7.1%}" if emitted else "      —"
        lines.append(
            f"{cls:<12} {n:>10} {share:>8} "
            f"{seconds.get(cls, 0.0):>10.4f}")
    gp = payload.get("goodput")
    lines.append(
        f"emitted={emitted} unsettled={payload.get('unsettled', 0)} "
        f"goodput={'—' if gp is None else format(gp, '.1%')}")
    eng = payload.get("slot_engine") or {}
    if eng.get("segments"):
        bf = eng.get("bubble_fraction")
        lines.append(
            f"slot engine: segments={eng['segments']} "
            f"row_steps={eng['row_steps']} live_steps={eng['live_steps']} "
            f"bubble_fraction="
            f"{'—' if bf is None else format(bf, '.3f')}")
    roof = payload.get("roofline") or {}
    progs = roof.get("programs") or {}
    if progs:
        peak = roof.get("peak_flops")
        kind = roof.get("device_kind") or "unknown"
        peak_s = f"{peak:.3g}" if peak else "none"
        lines.append(f"roofline (device={kind} peak_flops={peak_s}):")
        lines.append(
            f"{'PROGRAM':<12} {'FLOPS/TOK':>12} {'BYTES/TOK':>12} "
            f"{'INTENSITY':>10} {'MFU':>8}")
        for name in sorted(progs):
            d = progs[name]
            def _n(v, fmt=".3g"):
                return "—" if v is None else format(v, fmt)
            util = d.get("utilization")
            lines.append(
                f"{name:<12} {_n(d.get('flops_per_token')):>12} "
                f"{_n(d.get('bytes_per_token')):>12} "
                f"{_n(d.get('arithmetic_intensity')):>10} "
                f"{'null' if util is None else format(util, '.2%'):>8}")
    return "\n".join(lines) + "\n"


def fetch_ledger(target: str, timeout: float = 5.0) -> dict:
    """GET ``/debug/ledger`` from ``host:port`` (scheme/path optional,
    mirroring fetch_profile's target normalization)."""
    t = target.strip()
    if "//" not in t:
        t = "http://" + t
    if not t.rstrip("/").endswith("/debug/ledger"):
        t = t.rstrip("/") + "/debug/ledger"
    with urllib.request.urlopen(t, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))
