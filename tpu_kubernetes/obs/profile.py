"""Device-synced phase profiling: compile-vs-execute attribution + HBM.

The metrics layer (obs/metrics.py) answers *what* is slow — request
latency, step seconds. This module answers *why*: under jax's async
dispatch a wall-clock timer around a jitted call measures dispatch, not
work, and the first call of a program silently pays trace+compile. The
TPU serving/training comparisons the roadmap targets stand or fall on
separating those (PAPERS.md: Gemma-on-Cloud-TPU, Podracer), so
:class:`PhaseProfiler` makes the split explicit:

* ``phase(name, key=...)`` is a context manager that times a block and
  classifies it as ``mode="compile"`` (first time this ``key`` runs —
  trace+compile included) or ``mode="execute"`` (steady state). The
  yielded handle's :meth:`~PhaseHandle.sync` registers device values to
  ``jax.block_until_ready`` before the clock stops, so the recorded
  time is device time, not dispatch time.
* Each exit samples :func:`device_memory_stats` — HBM bytes-in-use /
  peak watermark on TPU/GPU backends, a graceful ``None`` on CPU.
* Pre-measured durations (the trainer's windowed step accounting, a
  decode loop's accumulated tail) go in via :meth:`~PhaseProfiler.observe`.
* Stats land in three sinks at once: a per-(phase, mode) histogram in
  the process REGISTRY (scrape-ready), an exact running aggregate for
  :meth:`~PhaseProfiler.summary` (what ``GET /debug/profile`` and
  ``tpu-kubernetes get profile`` render), and — when a ``tracer`` is
  passed — a child span whose ``meta`` carries mode + device seconds,
  so per-request attribution shows up in ``GET /debug/trace/<id>``.

No jax import at module load: the CLI renders remote profiles without
an accelerator stack, and the serve server must import without jax.
"""

from __future__ import annotations

import contextlib
import functools
import json
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field

from tpu_kubernetes.obs.metrics import DEFAULT_BUCKETS, REGISTRY, Registry

COMPILE = "compile"
EXECUTE = "execute"


def _block_until_ready(value) -> None:
    """Wait for device computation backing ``value`` (any pytree).
    No-op when jax is unavailable or the value is host-only."""
    try:
        import jax
    except Exception:
        return
    try:
        jax.block_until_ready(value)
    except Exception:
        # host-only values (ints, strings) and closed backends must not
        # turn a timing probe into a crash
        pass


def device_memory_stats():
    """HBM stats of the first addressable device, or ``None``.

    Returns ``{"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}``
    (whichever keys the backend reports) on TPU/GPU; ``None`` on CPU
    backends that don't track memory, when jax is missing, or on any
    backend error — profiling must never take the profiled process down.
    """
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
    out = {k: int(stats[k]) for k in keep if k in stats}
    return out or None


@dataclass
class PhaseHandle:
    """Yielded by :meth:`PhaseProfiler.phase`; call :meth:`sync` on the
    block's device outputs so the timer includes their computation."""

    name: str
    mode: str
    _pending: object = None

    def sync(self, value):
        """Register ``value`` to be blocked on before the clock stops.
        Returns ``value`` so it can wrap an expression in place."""
        self._pending = value
        return value


@dataclass
class _Stat:
    count: int = 0
    total: float = 0.0
    min: float = float("inf")
    max: float = 0.0
    last: float = 0.0

    def add(self, seconds: float, calls: int = 1) -> None:
        self.count += calls
        self.total += seconds
        per = seconds / max(1, calls)
        self.min = min(self.min, per)
        self.max = max(self.max, per)
        self.last = per

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "total_seconds": round(self.total, 6),
            "mean_seconds": round(self.total / max(1, self.count), 6),
            "min_seconds": round(self.min, 6),
            "max_seconds": round(self.max, 6),
            "last_seconds": round(self.last, 6),
        }


@dataclass
class PhaseRecord:
    name: str
    mode: str
    seconds: float
    ts: float
    meta: dict = field(default_factory=dict)
    hbm: dict | None = None


class PhaseProfiler:
    """Thread-safe phase timer with first-call (compile) detection.

    ``key`` identifies *a compiled program*: the first ``phase()`` entry
    for a given ``(name, key)`` is recorded as ``mode="compile"`` (jit
    trace + XLA compile ride on that call), every later one as
    ``mode="execute"``. Omitting ``key`` keys on the name alone. A block
    that raises does not consume first-call status — the compile really
    happens on the next successful run.
    """

    def __init__(self, registry: Registry | None = None, *,
                 metric: str = "tpu_profile_phase_seconds",
                 help: str = "device-synced phase seconds by compile/execute mode",
                 max_records: int = 2048,
                 sample_hbm: bool = True,
                 buckets=DEFAULT_BUCKETS):
        self._registry = registry if registry is not None else REGISTRY
        self.metric = metric
        self._hist = self._registry.histogram(
            metric, help, labelnames=("phase", "mode"), buckets=buckets)
        self._lock = threading.Lock()
        self._seen: set = set()
        self._stats: dict[tuple[str, str], _Stat] = {}
        self._records: deque[PhaseRecord] = deque(maxlen=max_records)
        self._last_hbm: dict | None = None
        self.sample_hbm = sample_hbm

    def mark_first(self, name: str, key=None) -> bool:
        """Check-and-mark first-call status for ``(name, key)`` without
        opening a phase — for call sites that split one logical phase
        across several timed regions (a decode loop's first step)."""
        k = (name, key)
        with self._lock:
            first = k not in self._seen
            self._seen.add(k)
        return first

    @contextlib.contextmanager
    def phase(self, name: str, key=None, tracer=None, **meta):
        """Time a block as phase ``name``. See class docstring.

        ``tracer`` (a :class:`tpu_kubernetes.util.trace.Tracer`) opens a
        nested quiet span and stamps mode / device seconds / HBM into
        its ``meta`` so the request trace carries the attribution.
        """
        k = (name, key)
        with self._lock:
            first = k not in self._seen
            self._seen.add(k)
        mode = COMPILE if first else EXECUTE
        handle = PhaseHandle(name=name, mode=mode)
        ctx = (tracer.phase(name, quiet=True, **meta)
               if tracer is not None else contextlib.nullcontext())
        with ctx as span:
            t0 = time.perf_counter()
            try:
                yield handle
            except BaseException:
                with self._lock:
                    if first:
                        self._seen.discard(k)
                raise
            _block_until_ready(handle._pending)
            seconds = time.perf_counter() - t0
            hbm = device_memory_stats() if self.sample_hbm else None
            self._record(name, mode, seconds, meta=meta, hbm=hbm)
            if span is not None:
                span.meta["mode"] = mode
                span.meta["device_seconds"] = round(seconds, 6)
                if hbm and "peak_bytes_in_use" in hbm:
                    span.meta["hbm_peak_mb"] = round(
                        hbm["peak_bytes_in_use"] / 2**20, 1)

    def wrap(self, name: str, key=None):
        """Decorator form: times each call, syncing the return value."""
        def deco(fn):
            @functools.wraps(fn)
            def inner(*args, **kwargs):
                with self.phase(name, key=key) as p:
                    return p.sync(fn(*args, **kwargs))
            return inner
        return deco

    def observe(self, name: str, seconds: float, *, mode: str = EXECUTE,
                calls: int = 1, **meta) -> None:
        """Record an externally measured duration. ``calls`` spreads the
        duration over that many invocations in the aggregate (one
        histogram observation either way — it is one measured region)."""
        hbm = device_memory_stats() if self.sample_hbm else None
        self._record(name, mode, seconds, calls=calls, meta=meta, hbm=hbm)

    def _record(self, name: str, mode: str, seconds: float, *,
                calls: int = 1, meta: dict | None = None,
                hbm: dict | None = None) -> None:
        self._hist.labels(name, mode).observe(seconds)
        with self._lock:
            self._stats.setdefault((name, mode), _Stat()).add(seconds, calls)
            self._records.append(PhaseRecord(
                name=name, mode=mode, seconds=seconds, ts=time.time(),
                meta=dict(meta or {}), hbm=hbm))
            if hbm:
                self._last_hbm = hbm

    def stat(self, name: str, mode: str) -> dict | None:
        with self._lock:
            s = self._stats.get((name, mode))
            return s.as_dict() if s else None

    def records(self, n: int = 50) -> list[dict]:
        with self._lock:
            recent = list(self._records)[-n:]
        return [
            {
                "phase": r.name, "mode": r.mode,
                "seconds": round(r.seconds, 6), "ts": r.ts,
                **({"meta": r.meta} if r.meta else {}),
                **({"hbm": r.hbm} if r.hbm else {}),
            }
            for r in recent
        ]

    def summary(self) -> dict:
        """Per-phase compile/execute aggregates + latest HBM sample —
        the ``GET /debug/profile`` payload."""
        with self._lock:
            stats = {k: s.as_dict() for k, s in self._stats.items()}
            hbm = dict(self._last_hbm) if self._last_hbm else None
        phases: dict[str, dict] = {}
        for (name, mode), d in sorted(stats.items()):
            phases.setdefault(name, {})[mode] = d
        for name, modes in phases.items():
            comp = modes.get(COMPILE)
            execu = modes.get(EXECUTE)
            if comp and execu:
                # what the first call paid beyond a steady-state run —
                # the trace+compile overhead this profiler exists to expose
                modes["compile_overhead_seconds"] = round(
                    max(0.0, comp["last_seconds"] - execu["mean_seconds"]), 6)
        return {"metric": self.metric, "phases": phases, "hbm": hbm}

    def reset(self) -> None:
        """Drop first-call marks, aggregates and records (tests)."""
        with self._lock:
            self._seen.clear()
            self._stats.clear()
            self._records.clear()
            self._last_hbm = None


def render_profile(summary: dict) -> str:
    """The ``tpu-kubernetes get profile`` table for a summary dict."""
    phases = summary.get("phases") or {}
    lines = [
        f"{'PHASE':<12} {'MODE':<8} {'CALLS':>6} {'TOTAL_S':>9} "
        f"{'MEAN_S':>9} {'LAST_S':>9}"
    ]
    if not phases:
        lines.append("(no phases recorded yet)")
    for name in sorted(phases):
        modes = phases[name]
        for mode in (COMPILE, EXECUTE):
            d = modes.get(mode)
            if not d:
                continue
            lines.append(
                f"{name:<12} {mode:<8} {d['count']:>6} "
                f"{d['total_seconds']:>9.4f} {d['mean_seconds']:>9.4f} "
                f"{d['last_seconds']:>9.4f}")
        overhead = modes.get("compile_overhead_seconds")
        if overhead is not None:
            lines.append(
                f"{name:<12} {'— compile overhead:':<25}"
                f"{overhead:>10.4f}s")
    hbm = summary.get("hbm")
    if hbm:
        parts = [f"{k}={v / 2**20:.1f}MiB" for k, v in sorted(hbm.items())]
        lines.append("hbm: " + " ".join(parts))
    return "\n".join(lines) + "\n"


def fetch_profile(target: str, timeout: float = 5.0) -> dict:
    """GET ``/debug/profile`` from ``host:port`` (scheme/path optional,
    mirroring the aggregate scraper's target normalization)."""
    t = target.strip()
    if "//" not in t:
        t = "http://" + t
    if not t.rstrip("/").endswith("/debug/profile"):
        t = t.rstrip("/") + "/debug/profile"
    with urllib.request.urlopen(t, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))
