"""Device-synced phase profiling: compile-vs-execute attribution + HBM.

The metrics layer (obs/metrics.py) answers *what* is slow — request
latency, step seconds. This module answers *why*: under jax's async
dispatch a wall-clock timer around a jitted call measures dispatch, not
work, and the first call of a program silently pays trace+compile. The
TPU serving/training comparisons the roadmap targets stand or fall on
separating those (PAPERS.md: Gemma-on-Cloud-TPU, Podracer), so
:class:`PhaseProfiler` makes the split explicit:

* ``phase(name, key=...)`` is a context manager that times a block and
  classifies it as ``mode="compile"`` (first time this ``key`` runs —
  trace+compile included) or ``mode="execute"`` (steady state). The
  yielded handle's :meth:`~PhaseHandle.sync` registers device values to
  ``jax.block_until_ready`` before the clock stops, so the recorded
  time is device time, not dispatch time.
* Each exit samples :func:`device_memory_stats` — HBM bytes-in-use /
  peak watermark on TPU/GPU backends, a graceful ``None`` on CPU.
* Pre-measured durations (the trainer's windowed step accounting, a
  decode loop's accumulated tail) go in via :meth:`~PhaseProfiler.observe`.
* Stats land in three sinks at once: a per-(phase, mode) histogram in
  the process REGISTRY (scrape-ready), an exact running aggregate for
  :meth:`~PhaseProfiler.summary` (what ``GET /debug/profile`` and
  ``tpu-kubernetes get profile`` render), and — when a ``tracer`` is
  passed — a child span whose ``meta`` carries mode + device seconds,
  so per-request attribution shows up in ``GET /debug/trace/<id>``.

No jax import at module load: the CLI renders remote profiles without
an accelerator stack, and the serve server must import without jax.
"""

from __future__ import annotations

import contextlib
import functools
import json
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field

from tpu_kubernetes.obs.metrics import DEFAULT_BUCKETS, REGISTRY, Registry

COMPILE = "compile"
EXECUTE = "execute"


def _block_until_ready(value) -> None:
    """Wait for device computation backing ``value`` (any pytree).
    No-op when jax is unavailable or the value is host-only."""
    try:
        import jax
    except Exception:
        return
    try:
        jax.block_until_ready(value)
    except Exception:
        # host-only values (ints, strings) and closed backends must not
        # turn a timing probe into a crash
        pass


def device_memory_stats():
    """HBM stats of the first addressable device, or ``None``.

    Returns ``{"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}``
    (whichever keys the backend reports) on TPU/GPU; ``None`` on CPU
    backends that don't track memory, when jax is missing, or on any
    backend error — profiling must never take the profiled process down.
    """
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
    out = {k: int(stats[k]) for k in keep if k in stats}
    return out or None


# analytical roofline: bf16 dense peak FLOP/s per accelerator, matched
# by substring against jax's device_kind (v5e reports "TPU v5 lite").
# CPU and unknown backends get None — utilization degrades to null
# while FLOPs/token (an XLA cost-model fact) stays exact everywhere.
PEAK_FLOPS = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("h100", 989e12),
    ("a100", 312e12),
)


def device_kind() -> str | None:
    """The first addressable device's kind string ("cpu", "TPU v5e",
    ...), or ``None`` when jax is unavailable."""
    try:
        import jax
        return str(jax.devices()[0].device_kind)
    except Exception:
        return None


def backend_peak_flops(kind: str | None = None) -> float | None:
    """Dense bf16 peak FLOP/s for the backend, or ``None`` on CPU /
    unknown hardware (same graceful-degradation stance as
    :func:`device_memory_stats`)."""
    kind = kind if kind is not None else device_kind()
    if not kind:
        return None
    k = kind.lower()
    if "cpu" in k:
        return None
    for sub, peak in PEAK_FLOPS:
        if sub in k:
            return peak
    return None


def program_cost(fn, *args, **kwargs) -> dict | None:
    """XLA's analytical cost model for one call of jitted ``fn``:
    ``{"flops", "bytes"}`` per invocation, via ``lower().cost_analysis()``
    (a trace, not a compile — and crucially not a *call*, so donated
    buffers stay alive for the real invocation that follows). Returns
    ``None`` when the backend or program doesn't report costs."""
    try:
        lowered = fn.lower(*args, **kwargs)
        cost = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):   # older jax: list of dicts
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    out = {}
    flops = cost.get("flops")
    if flops is not None and float(flops) > 0:
        out["flops"] = float(flops)
    nbytes = cost.get("bytes accessed")
    if nbytes is not None and float(nbytes) > 0:
        out["bytes"] = float(nbytes)
    return out or None


@dataclass
class PhaseHandle:
    """Yielded by :meth:`PhaseProfiler.phase`; call :meth:`sync` on the
    block's device outputs so the timer includes their computation."""

    name: str
    mode: str
    _pending: object = None

    def sync(self, value):
        """Register ``value`` to be blocked on before the clock stops.
        Returns ``value`` so it can wrap an expression in place."""
        self._pending = value
        return value


@dataclass
class _Stat:
    count: int = 0
    total: float = 0.0
    min: float = float("inf")
    max: float = 0.0
    last: float = 0.0

    def add(self, seconds: float, calls: int = 1) -> None:
        self.count += calls
        self.total += seconds
        per = seconds / max(1, calls)
        self.min = min(self.min, per)
        self.max = max(self.max, per)
        self.last = per

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "total_seconds": round(self.total, 6),
            "mean_seconds": round(self.total / max(1, self.count), 6),
            "min_seconds": round(self.min, 6),
            "max_seconds": round(self.max, 6),
            "last_seconds": round(self.last, 6),
        }


@dataclass
class PhaseRecord:
    name: str
    mode: str
    seconds: float
    ts: float
    meta: dict = field(default_factory=dict)
    hbm: dict | None = None


class PhaseProfiler:
    """Thread-safe phase timer with first-call (compile) detection.

    ``key`` identifies *a compiled program*: the first ``phase()`` entry
    for a given ``(name, key)`` is recorded as ``mode="compile"`` (jit
    trace + XLA compile ride on that call), every later one as
    ``mode="execute"``. Omitting ``key`` keys on the name alone. A block
    that raises does not consume first-call status — the compile really
    happens on the next successful run.
    """

    def __init__(self, registry: Registry | None = None, *,
                 metric: str = "tpu_profile_phase_seconds",
                 help: str = "device-synced phase seconds by compile/execute mode",
                 max_records: int = 2048,
                 sample_hbm: bool = True,
                 buckets=DEFAULT_BUCKETS):
        self._registry = registry if registry is not None else REGISTRY
        self.metric = metric
        self._hist = self._registry.histogram(
            metric, help, labelnames=("phase", "mode"), buckets=buckets)
        self._lock = threading.Lock()
        self._seen: set = set()
        self._stats: dict[tuple[str, str], _Stat] = {}
        self._records: deque[PhaseRecord] = deque(maxlen=max_records)
        self._last_hbm: dict | None = None
        self.sample_hbm = sample_hbm
        # analytical roofline: per-phase {flops, bytes, tokens} from the
        # last captured program (record_cost), keyed like phases so
        # utilization can divide by that phase's execute-mode mean
        self._cost_seen: set = set()
        self._costs: dict[str, dict] = {}

    def mark_first(self, name: str, key=None) -> bool:
        """Check-and-mark first-call status for ``(name, key)`` without
        opening a phase — for call sites that split one logical phase
        across several timed regions (a decode loop's first step)."""
        k = (name, key)
        with self._lock:
            first = k not in self._seen
            self._seen.add(k)
        return first

    @contextlib.contextmanager
    def phase(self, name: str, key=None, tracer=None, **meta):
        """Time a block as phase ``name``. See class docstring.

        ``tracer`` (a :class:`tpu_kubernetes.util.trace.Tracer`) opens a
        nested quiet span and stamps mode / device seconds / HBM into
        its ``meta`` so the request trace carries the attribution.
        """
        k = (name, key)
        with self._lock:
            first = k not in self._seen
            self._seen.add(k)
        mode = COMPILE if first else EXECUTE
        handle = PhaseHandle(name=name, mode=mode)
        ctx = (tracer.phase(name, quiet=True, **meta)
               if tracer is not None else contextlib.nullcontext())
        with ctx as span:
            t0 = time.perf_counter()
            try:
                yield handle
            except BaseException:
                with self._lock:
                    if first:
                        self._seen.discard(k)
                raise
            _block_until_ready(handle._pending)
            seconds = time.perf_counter() - t0
            hbm = device_memory_stats() if self.sample_hbm else None
            self._record(name, mode, seconds, meta=meta, hbm=hbm)
            if span is not None:
                span.meta["mode"] = mode
                span.meta["device_seconds"] = round(seconds, 6)
                if hbm and "peak_bytes_in_use" in hbm:
                    span.meta["hbm_peak_mb"] = round(
                        hbm["peak_bytes_in_use"] / 2**20, 1)

    def wrap(self, name: str, key=None):
        """Decorator form: times each call, syncing the return value."""
        def deco(fn):
            @functools.wraps(fn)
            def inner(*args, **kwargs):
                with self.phase(name, key=key) as p:
                    return p.sync(fn(*args, **kwargs))
            return inner
        return deco

    def observe(self, name: str, seconds: float, *, mode: str = EXECUTE,
                calls: int = 1, **meta) -> None:
        """Record an externally measured duration. ``calls`` spreads the
        duration over that many invocations in the aggregate (one
        histogram observation either way — it is one measured region)."""
        hbm = device_memory_stats() if self.sample_hbm else None
        self._record(name, mode, seconds, calls=calls, meta=meta, hbm=hbm)

    def _record(self, name: str, mode: str, seconds: float, *,
                calls: int = 1, meta: dict | None = None,
                hbm: dict | None = None) -> None:
        self._hist.labels(name, mode).observe(seconds)
        with self._lock:
            self._stats.setdefault((name, mode), _Stat()).add(seconds, calls)
            self._records.append(PhaseRecord(
                name=name, mode=mode, seconds=seconds, ts=time.time(),
                meta=dict(meta or {}), hbm=hbm))
            if hbm:
                self._last_hbm = hbm

    def record_cost(self, name: str, fn, args=(), kwargs=None, *,
                    tokens: int | None = None, key=None) -> None:
        """Capture XLA's analytical FLOPs/bytes for program ``(name,
        key)`` — once, on its first sighting, and BEFORE the program's
        first call (lowering needs the concrete args, and donated
        buffers are deleted by the call). ``tokens`` is how many tokens
        one invocation produces/consumes, making FLOPs-per-token exact
        even on backends with no peak-FLOPs entry."""
        k = (name, key)
        with self._lock:
            if k in self._cost_seen:
                return
            self._cost_seen.add(k)
        cost = program_cost(fn, *args, **(kwargs or {}))
        if cost is None:
            return
        entry = {
            "flops": cost.get("flops"),
            "bytes": cost.get("bytes"),
            "tokens": int(tokens) if tokens else None,
        }
        with self._lock:
            self._costs[name] = entry

    def roofline(self) -> dict:
        """Analytical MFU/roofline attribution: per captured program,
        FLOPs/bytes per token and arithmetic intensity (exact from the
        XLA cost model, CPU included), plus utilization = achieved
        FLOP/s over the backend's dense peak — ``None`` whenever the
        peak (CPU) or a steady-state execute mean is unknown."""
        kind = device_kind()
        peak = backend_peak_flops(kind)
        with self._lock:
            costs = {n: dict(d) for n, d in self._costs.items()}
            exec_means = {
                name: s.total / s.count
                for (name, mode), s in self._stats.items()
                if mode == EXECUTE and s.count
            }
        programs: dict[str, dict] = {}
        for name in sorted(costs):
            d = costs[name]
            flops, nbytes, toks = d["flops"], d["bytes"], d["tokens"]
            prog = {
                "flops": flops,
                "bytes": nbytes,
                "tokens_per_call": toks,
                "flops_per_token": (round(flops / toks, 3)
                                    if flops and toks else None),
                "bytes_per_token": (round(nbytes / toks, 3)
                                    if nbytes and toks else None),
                "arithmetic_intensity": (round(flops / nbytes, 3)
                                         if flops and nbytes else None),
                "utilization": None,
            }
            mean = exec_means.get(name)
            if peak and flops and mean and mean > 0:
                prog["utilization"] = round(flops / (mean * peak), 6)
            programs[name] = prog
        return {"device_kind": kind, "peak_flops": peak,
                "programs": programs}

    def stat(self, name: str, mode: str) -> dict | None:
        with self._lock:
            s = self._stats.get((name, mode))
            return s.as_dict() if s else None

    def records(self, n: int = 50) -> list[dict]:
        with self._lock:
            recent = list(self._records)[-n:]
        return [
            {
                "phase": r.name, "mode": r.mode,
                "seconds": round(r.seconds, 6), "ts": r.ts,
                **({"meta": r.meta} if r.meta else {}),
                **({"hbm": r.hbm} if r.hbm else {}),
            }
            for r in recent
        ]

    def summary(self) -> dict:
        """Per-phase compile/execute aggregates + latest HBM sample —
        the ``GET /debug/profile`` payload."""
        with self._lock:
            stats = {k: s.as_dict() for k, s in self._stats.items()}
            hbm = dict(self._last_hbm) if self._last_hbm else None
        phases: dict[str, dict] = {}
        for (name, mode), d in sorted(stats.items()):
            phases.setdefault(name, {})[mode] = d
        for name, modes in phases.items():
            comp = modes.get(COMPILE)
            execu = modes.get(EXECUTE)
            if comp and execu:
                # what the first call paid beyond a steady-state run —
                # the trace+compile overhead this profiler exists to expose
                modes["compile_overhead_seconds"] = round(
                    max(0.0, comp["last_seconds"] - execu["mean_seconds"]), 6)
        return {"metric": self.metric, "phases": phases, "hbm": hbm,
                "roofline": self.roofline()}

    def reset(self) -> None:
        """Drop first-call marks, aggregates and records (tests)."""
        with self._lock:
            self._seen.clear()
            self._stats.clear()
            self._records.clear()
            self._last_hbm = None
            self._cost_seen.clear()
            self._costs.clear()


def render_profile(summary: dict) -> str:
    """The ``tpu-kubernetes get profile`` table for a summary dict."""
    phases = summary.get("phases") or {}
    lines = [
        f"{'PHASE':<12} {'MODE':<8} {'CALLS':>6} {'TOTAL_S':>9} "
        f"{'MEAN_S':>9} {'LAST_S':>9}"
    ]
    if not phases:
        lines.append("(no phases recorded yet)")
    for name in sorted(phases):
        modes = phases[name]
        for mode in (COMPILE, EXECUTE):
            d = modes.get(mode)
            if not d:
                continue
            lines.append(
                f"{name:<12} {mode:<8} {d['count']:>6} "
                f"{d['total_seconds']:>9.4f} {d['mean_seconds']:>9.4f} "
                f"{d['last_seconds']:>9.4f}")
        overhead = modes.get("compile_overhead_seconds")
        if overhead is not None:
            lines.append(
                f"{name:<12} {'— compile overhead:':<25}"
                f"{overhead:>10.4f}s")
    hbm = summary.get("hbm")
    if hbm:
        parts = [f"{k}={v / 2**20:.1f}MiB" for k, v in sorted(hbm.items())]
        lines.append("hbm: " + " ".join(parts))
    roof = summary.get("roofline") or {}
    progs = roof.get("programs") or {}
    if progs:
        peak = roof.get("peak_flops")
        lines.append(
            f"roofline (device={roof.get('device_kind') or 'unknown'} "
            f"peak_flops={f'{peak:.3g}' if peak else 'none'}):")
        lines.append(
            f"{'PROGRAM':<12} {'FLOPS/TOK':>12} {'BYTES/TOK':>12} "
            f"{'INTENSITY':>10} {'MFU':>8}")
        for name in sorted(progs):
            d = progs[name]
            fmt = lambda v: "—" if v is None else format(v, ".3g")  # noqa: E731
            util = d.get("utilization")
            lines.append(
                f"{name:<12} {fmt(d.get('flops_per_token')):>12} "
                f"{fmt(d.get('bytes_per_token')):>12} "
                f"{fmt(d.get('arithmetic_intensity')):>10} "
                f"{'null' if util is None else format(util, '.2%'):>8}")
    return "\n".join(lines) + "\n"


def fetch_profile(target: str, timeout: float = 5.0) -> dict:
    """GET ``/debug/profile`` from ``host:port`` (scheme/path optional,
    mirroring the aggregate scraper's target normalization)."""
    t = target.strip()
    if "//" not in t:
        t = "http://" + t
    if not t.rstrip("/").endswith("/debug/profile"):
        t = t.rstrip("/") + "/debug/profile"
    with urllib.request.urlopen(t, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))
