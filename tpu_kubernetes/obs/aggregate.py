"""Fleet-scope metrics aggregation: scrape N workers, merge one view.

PR 1 gave every process its own registry and ``GET /metrics``; this is
the layer that can see the fleet (ROADMAP north star: many serve/train
workers behind one operator). A :class:`FleetAggregator` polls each
target's ``/metrics`` concurrently (bounded by per-request timeouts and
a retry), parses the exposition (obs/expfmt.py), tags every sample with
an ``instance`` label, and merges the lot into one
:class:`FleetSnapshot` — which renders back out as exposition (the
aggregator is itself scrape-able) and answers the queries the SLO
evaluator (obs/slo.py) and the ``monitor`` CLI ask.

Per-target scrape health is first-class: ``up`` (the Prometheus
convention — 1 scraped, 0 failed), scrape latency, and consecutive
failure counts survive across cycles, so one dead worker reads as
``up=0`` without failing the cycle for its siblings.
"""

from __future__ import annotations

import json
import math
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field, replace
from typing import Callable

from tpu_kubernetes.obs import expfmt, tracing
from tpu_kubernetes.obs.faults import FAULTS

# synthetic per-target families the aggregator itself contributes
UP = "up"
SCRAPE_SECONDS = "fleet_scrape_duration_seconds"
SCRAPE_FAILURES = "fleet_scrape_consecutive_failures"
SCRAPE_BACKOFF = "fleet_scrape_backoff_seconds"

# exponential backoff cap, as a multiple of the base interval: a target
# that stays dead is re-polled at ~8x the normal period, not never
BACKOFF_CAP_MULT = 8.0

# per-instance saturation score: each component maps to [0, 1) via
# x / (x + half), where ``half`` is the reading at which the component
# scores 0.5 — the score is the MAX component (the binding constraint),
# which is what a placement decision actually routes away from
SAT_WAIT_HALF_S = 0.25   # admission-wait EWMA seconds scoring 0.5
SAT_QUEUE_HALF = 8.0     # inflight requests scoring 0.5
SAT_SLOTS_HALF = 2.0     # mean live slot rows scoring 0.5
SAT_EWMA_ALPHA = 0.3     # per-cycle smoothing of the admission wait


@dataclass
class TargetHealth:
    instance: str
    up: int = 0
    consecutive_failures: int = 0
    last_scrape_seconds: float = 0.0
    last_error: str = ""
    last_success_ts: float = 0.0
    backoff_s: float = 0.0       # current penalty (0 = none / disabled)
    next_scrape_ts: float = 0.0  # skip scrapes until this timestamp
    # instance lifecycle from GET /healthz ("serving"/"warming"/
    # "draining"/"failed"; "" = unknown or probing disabled) — what
    # distinguishes a draining instance from a merely saturated one
    lifecycle: str = ""


@dataclass
class FleetSnapshot:
    """One merged scrape cycle: every worker's families with ``instance``
    labels, plus the synthetic health families."""

    ts: float
    health: dict[str, TargetHealth]
    families: dict[str, expfmt.Family]

    def instances(self) -> list[str]:
        return sorted(self.health)

    def render(self) -> str:
        """The merged view as text exposition (name-ordered, like the
        per-process registry) — the aggregator re-exposes losslessly."""
        return expfmt.render(
            [self.families[n] for n in sorted(self.families)]
        )

    # -- queries (what obs/slo.py and the monitor table read) --------------

    def _samples(self, sample_name: str, family: str,
                 where: Callable[[dict[str, str]], bool] | None):
        fam = self.families.get(family)
        if fam is None:
            return
        for s in fam.samples:
            if s.name != sample_name:
                continue
            if where is None or where(s.labels_dict()):
                yield s

    def value_sum(self, name: str,
                  where: Callable[[dict[str, str]], bool] | None = None,
                  ) -> float:
        """Sum a counter/gauge family's samples across the fleet
        (optionally filtered by a labels predicate, e.g. one instance)."""
        return sum(s.value for s in self._samples(name, name, where))

    def histogram_buckets(self, name: str,
                          where: Callable[[dict[str, str]], bool] | None = None,
                          ) -> list[tuple[float, float]]:
        """Cumulative ``(le, count)`` pairs for a histogram family,
        bucket-wise summed across matching series (le grids are shared —
        every worker runs the same instrumentation)."""
        acc: dict[float, float] = {}
        for s in self._samples(f"{name}_bucket", name, where):
            le = expfmt.parse_value(s.labels_dict().get("le", "+Inf"))
            acc[le] = acc.get(le, 0.0) + s.value
        return sorted(acc.items())

    def histogram_count(self, name: str,
                        where: Callable[[dict[str, str]], bool] | None = None,
                        ) -> float:
        return sum(s.value for s in self._samples(f"{name}_count", name, where))

    def histogram_sum(self, name: str,
                      where: Callable[[dict[str, str]], bool] | None = None,
                      ) -> float:
        return sum(s.value for s in self._samples(f"{name}_sum", name, where))

    def quantile(self, name: str, q: float,
                 where: Callable[[dict[str, str]], bool] | None = None,
                 ) -> float | None:
        return expfmt.bucket_quantile(self.histogram_buckets(name, where), q)

    def label_value(self, family: str, label: str,
                    where: Callable[[dict[str, str]], bool] | None = None,
                    ) -> str | None:
        """First matching sample's value for one LABEL — how ``*_info``
        idiom families are read (e.g. the ``version`` a worker's
        ``tpu_k8s_build_info`` carries), where the sample value is a
        constant 1 and the payload rides the labels."""
        fam = self.families.get(family)
        if fam is None:
            return None
        for s in fam.samples:
            d = s.labels_dict()
            if where is not None and not where(d):
                continue
            if label in d:
                return d[label]
        return None


@dataclass
class ScrapeResult:
    instance: str
    ok: bool
    seconds: float
    families: list[expfmt.Family] = field(default_factory=list)
    error: str = ""
    lifecycle: str = ""


def _normalize_target(target: str) -> tuple[str, str]:
    """``host:port`` (or a full URL) → (instance label, scrape URL)."""
    target = target.strip()
    if "://" not in target:
        return target, f"http://{target}/metrics"
    rest = target.split("://", 1)[1]
    instance = rest.split("/", 1)[0]
    if rest == instance:  # bare scheme://host:port — default the path
        return instance, f"{target.rstrip('/')}/metrics"
    return instance, target


class FleetAggregator:
    """Thread-safe multi-target scraper. ``scrape_once`` may be called
    from any thread (the monitor loop, a test, a future autoscaler);
    health state is cumulative across cycles under one lock."""

    def __init__(self, targets: list[str], timeout_s: float = 2.0,
                 retries: int = 1, max_workers: int = 16,
                 backoff_base_s: float = 0.0, tsdb=None, alerts=None,
                 probe_health: bool = False):
        self._targets = [_normalize_target(t) for t in targets]
        if not self._targets:
            raise ValueError("FleetAggregator needs at least one target")
        self.timeout_s = timeout_s
        self.retries = max(0, int(retries))
        # probe_health=True adds one GET /healthz per target per cycle,
        # recording the instance lifecycle (serving/warming/draining/
        # failed) alongside the scrape — bare exporters without a
        # healthz endpoint simply read as "" (unknown)
        self.probe_health = bool(probe_health)
        # backoff_base_s > 0 (callers pass their poll interval) arms
        # jittered exponential backoff for dead targets: consecutive
        # failures double the re-poll delay up to BACKOFF_CAP_MULT x the
        # base, reset on success. 0 keeps every cycle scraping every
        # target (one-shot callers want the immediate answer).
        self.backoff_base_s = max(0.0, float(backoff_base_s))
        # a history store (obs/tsdb.py, duck-typed: .ingest(snapshot))
        # receives every merged cycle — the one source of truth the SLO
        # burn windows, monitor trends, and `get history` all read
        self._tsdb = tsdb
        # an alert manager (obs/alerts.py, duck-typed:
        # .evaluate(snapshot=, store=, now=)) evaluated against every
        # merged cycle — headless callers (a future autoscaler) get
        # rule evaluation without running the monitor loop
        self._alerts = alerts
        self._max_workers = max(1, min(max_workers, len(self._targets)))
        self._lock = threading.Lock()
        self._health: dict[str, TargetHealth] = {
            instance: TargetHealth(instance=instance)
            for instance, _ in self._targets
        }
        # admission-wait EWMA state per instance (scrape_once only —
        # single-writer, so it lives outside the health lock)
        self._sat_state: dict[str, dict] = {}

    def _fetch(self, url: str) -> str:
        # every outbound scrape carries W3C trace context — the scrape
        # itself becomes a span in the worker's ring, so a slow /metrics
        # endpoint is attributable like any other request
        req = urllib.request.Request(
            url, headers=tracing.outbound_headers({
                "Accept": "text/plain", "User-Agent": "tpu-k8s-monitor",
            }),
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    def _probe_lifecycle(self, instance: str, url: str) -> str:
        """GET the target's ``/healthz`` and map its ``status`` to the
        lifecycle label the monitor shows: ``ok`` → ``serving``; the
        503 states (``warming``/``draining``/``failed``) carry their
        status in the error body. Anything unparsable — a bare metrics
        exporter with no healthz — reads as ``""`` (unknown)."""
        probe = f"{url.split('://', 1)[0]}://{instance}/healthz"
        req = urllib.request.Request(probe, headers=tracing.outbound_headers({
            "Accept": "application/json", "User-Agent": "tpu-k8s-monitor",
        }))
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            try:
                body = e.read()
            except Exception:  # noqa: BLE001 — probe is best-effort
                return ""
        except Exception:  # noqa: BLE001 — probe is best-effort
            return ""
        try:
            status = json.loads(body.decode("utf-8", "replace")).get("status")
        except Exception:  # noqa: BLE001 — non-JSON healthz
            return ""
        if not isinstance(status, str):
            return ""
        return "serving" if status == "ok" else status

    def _scrape_target(self, instance: str, url: str) -> ScrapeResult:
        last_error = ""
        lifecycle = (
            self._probe_lifecycle(instance, url) if self.probe_health else ""
        )
        t0 = time.monotonic()
        for _ in range(self.retries + 1):
            try:
                FAULTS.fire("fleet.scrape")
                families = expfmt.parse(self._fetch(url))
            except Exception as e:  # noqa: BLE001 — per-target isolation
                last_error = f"{type(e).__name__}: {e}"[:200]
                continue
            return ScrapeResult(
                instance=instance, ok=True,
                seconds=time.monotonic() - t0, families=families,
                lifecycle=lifecycle,
            )
        return ScrapeResult(
            instance=instance, ok=False,
            seconds=time.monotonic() - t0, error=last_error,
            lifecycle=lifecycle,
        )

    def health(self) -> dict[str, TargetHealth]:
        with self._lock:
            return {i: replace(h) for i, h in self._health.items()}

    def _saturation_family(self, merged: dict, health: dict, *,
                           metric: str) -> expfmt.Family:
        """Per-instance saturation in [0, 1): the MAX of four component
        pressures — admission-wait EWMA (delta mean per cycle, smoothed
        by SAT_EWMA_ALPHA), mean live slot rows, free-page fraction
        (paged engines), and inflight queue depth — each squashed via
        ``x / (x + half)``. The ``role`` label joins the worker's
        ``tpu_serve_role_info`` gauge (SERVE_ROLE), so disaggregated
        prefill/decode tiers balance independently."""

        def val(family: str, sample_name: str, instance: str,
                extra: dict | None = None) -> float:
            fam = merged.get(family)
            if fam is None:
                return 0.0
            out = 0.0
            for s in fam.samples:
                if s.name != sample_name:
                    continue
                d = s.labels_dict()
                if d.get("instance") != instance:
                    continue
                if extra and any(d.get(k) != v for k, v in extra.items()):
                    continue
                out += s.value
            return out

        def role_of(instance: str) -> str:
            fam = merged.get("tpu_serve_role_info")
            if fam is not None:
                for s in fam.samples:
                    d = s.labels_dict()
                    if d.get("instance") == instance and "role" in d:
                        return d["role"]
            return ""

        aw = "tpu_serve_admission_wait_seconds"
        samples = []
        for i in sorted(health):
            wsum = val(aw, aw + "_sum", i)
            wcount = val(aw, aw + "_count", i)
            st = self._sat_state.get(i) or \
                {"sum": 0.0, "count": 0.0, "ewma": 0.0}
            dsum, dcount = wsum - st["sum"], wcount - st["count"]
            if dsum < 0 or dcount < 0:   # counter reset (worker restart)
                dsum, dcount = wsum, wcount
            if dcount > 0:
                st["ewma"] = (SAT_EWMA_ALPHA * (dsum / dcount)
                              + (1.0 - SAT_EWMA_ALPHA) * st["ewma"])
            st["sum"], st["count"] = wsum, wcount
            self._sat_state[i] = st
            wait_p = st["ewma"] / (st["ewma"] + SAT_WAIT_HALF_S)
            occ = val("tpu_serve_slot_occupancy",
                      "tpu_serve_slot_occupancy", i)
            occ_p = occ / (occ + SAT_SLOTS_HALF) if occ > 0 else 0.0
            q = val("tpu_serve_inflight_requests",
                    "tpu_serve_inflight_requests", i)
            q_p = q / (q + SAT_QUEUE_HALF) if q > 0 else 0.0
            pages = val("tpu_serve_kv_pages", "tpu_serve_kv_pages", i)
            free = val("tpu_serve_kv_pages", "tpu_serve_kv_pages", i,
                       {"state": "free"})
            page_p = (1.0 - free / pages) if pages > 0 else 0.0
            samples.append(expfmt.Sample(
                name=metric,
                labels=(("instance", i), ("role", role_of(i))),
                value=round(max(wait_p, occ_p, q_p, page_p), 6),
            ))
        return expfmt.Family(
            name=metric, kind="gauge",
            help="per-instance saturation score in [0,1): max of "
                 "admission-wait EWMA, slot occupancy, page pressure, "
                 "and queue-depth components (role joins SERVE_ROLE)",
            samples=samples,
        )

    def scrape_once(self, now: float | None = None) -> FleetSnapshot:
        """One fleet cycle: scrape every target concurrently, update
        health, and return the merged snapshot. A failing target never
        fails the cycle — it contributes ``up=0`` and keeps its last
        error on record."""
        now = time.time() if now is None else now
        # dead targets still inside their backoff window are skipped this
        # cycle (they keep their up=0 / failure-count reading); everyone
        # else scrapes concurrently
        with self._lock:
            due = [
                (instance, url) for instance, url in self._targets
                if not (self.backoff_base_s > 0
                        and self._health[instance].next_scrape_ts > now)
            ]
        results: list[ScrapeResult] = []
        if due:
            with ThreadPoolExecutor(
                max_workers=min(self._max_workers, len(due))
            ) as pool:
                results = list(pool.map(
                    lambda t: self._scrape_target(*t), due
                ))

        with self._lock:
            for r in results:
                h = self._health[r.instance]
                h.up = 1 if r.ok else 0
                h.lifecycle = r.lifecycle
                h.last_scrape_seconds = round(r.seconds, 6)
                if r.ok:
                    h.consecutive_failures = 0
                    h.last_error = ""
                    h.last_success_ts = now
                    h.backoff_s = 0.0
                    h.next_scrape_ts = 0.0
                else:
                    h.consecutive_failures += 1
                    h.last_error = r.error
                    if self.backoff_base_s > 0:
                        raw = min(
                            self.backoff_base_s
                            * 2.0 ** (h.consecutive_failures - 1),
                            BACKOFF_CAP_MULT * self.backoff_base_s,
                        )
                        # ±20% jitter so a fleet of aggregators doesn't
                        # re-poll a recovering target in lockstep
                        h.backoff_s = round(
                            raw * random.uniform(0.8, 1.2), 6
                        )
                        h.next_scrape_ts = now + h.backoff_s
            health = {i: replace(h) for i, h in self._health.items()}

        merged: dict[str, expfmt.Family] = {}
        for r in results:
            for fam in r.families:
                dst = merged.get(fam.name)
                if dst is None:
                    dst = merged[fam.name] = expfmt.Family(
                        name=fam.name, help=fam.help, kind=fam.kind
                    )
                dst.samples.extend(
                    s.with_label("instance", r.instance) for s in fam.samples
                )

        for name, help_, kind, value_of in (
            (UP, "1 if the target's last scrape succeeded", "gauge",
             lambda h: float(h.up)),
            (SCRAPE_SECONDS, "wall time of the target's last scrape",
             "gauge", lambda h: h.last_scrape_seconds),
            (SCRAPE_FAILURES, "scrape failures since the last success",
             "gauge", lambda h: float(h.consecutive_failures)),
            (SCRAPE_BACKOFF, "current re-poll backoff for the target "
             "(0 = healthy or backoff disabled)",
             "gauge", lambda h: h.backoff_s),
        ):
            merged[name] = expfmt.Family(
                name=name, help=help_, kind=kind,
                samples=[
                    expfmt.Sample(
                        name=name, labels=(("instance", i),),
                        value=value_of(health[i]),
                    )
                    for i in sorted(health)
                ],
            )
        sat = self._saturation_family(
            merged, health, metric="tpu_serve_saturation",
        )
        merged[sat.name] = sat
        snapshot = FleetSnapshot(ts=now, health=health, families=merged)
        if self._tsdb is not None:
            try:
                self._tsdb.ingest(snapshot)
            except Exception:  # noqa: BLE001 — history must not fail a scrape
                pass
        if self._alerts is not None:
            try:
                self._alerts.evaluate(snapshot=snapshot, store=self._tsdb,
                                      now=now)
            except Exception:  # noqa: BLE001 — alerting must not fail a scrape
                pass
        return snapshot


def rate(now_value: float, then_value: float, seconds: float) -> float | None:
    """Per-second rate between two cumulative readings; None when the
    elapsed window is degenerate. A negative delta means the counter
    reset (worker restarted between cycles) — Prometheus semantics treat
    ``then`` as 0, so the rate is the new value over the window rather
    than a negative or a blank."""
    if seconds <= 0 or not math.isfinite(seconds):
        return None
    delta = now_value - then_value
    if delta < 0:  # counter reset: everything since restart is increase
        delta = now_value
    return delta / seconds
