"""Structured run events: a JSONL sink with correlation IDs and nesting.

The human-facing channel is util/log (leveled stderr); the machine-facing
channel is this one — append-only JSON lines, one event per line, each
carrying:

  ``run``    the run/correlation id (one workflow invocation, one HTTP
             request, one training job) — set with :func:`run_context`
  ``span``   this event's span id (span_start/span_end pairs share one)
  ``parent`` the enclosing span's id, so nested phases reconstruct as a
             tree (terraform init inside apply manager inside the run)

The sink is disabled unless configured (``TPU_K8S_EVENTS=<path>`` or
:func:`configure`), and it NEVER raises: observability must not fail a
workflow (the util/runlog.py stance). Context flows through contextvars,
so concurrent server threads and nested workflow phases each see their
own run/parent without any plumbing through call signatures.
"""

from __future__ import annotations

import contextlib
import contextvars
import io
import json
import os
import threading
import time
import uuid

_run_id: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "tpu_k8s_run_id", default=None
)
_parent_span: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "tpu_k8s_parent_span", default=None
)


def new_id() -> str:
    """A short correlation id (12 hex chars — log-line friendly, and far
    beyond collision range for per-process event streams)."""
    return uuid.uuid4().hex[:12]


def current_run_id() -> str | None:
    return _run_id.get()


def current_span_id() -> str | None:
    return _parent_span.get()


DEFAULT_MAX_MB = 64.0
DEFAULT_KEEP = 1


def _max_bytes_from_env() -> int:
    from tpu_kubernetes.util.envparse import env_float

    mb = env_float("TPU_K8S_EVENTS_MAX_MB", DEFAULT_MAX_MB)
    return int(mb * 1024 * 1024)


def _keep_from_env() -> int:
    from tpu_kubernetes.util.envparse import env_int

    return max(1, env_int("TPU_K8S_EVENTS_KEEP", DEFAULT_KEEP))


class EventSink:
    """Thread-safe JSONL writer over a path or an open stream.

    Path sinks rotate by size so a long-lived server cannot fill a disk:
    when the file would exceed ``max_bytes`` (``TPU_K8S_EVENTS_MAX_MB``,
    default 64; ≤0 disables) generations cascade ``<path>.N-1 →
    <path>.N`` up to ``keep`` rotations (``TPU_K8S_EVENTS_KEEP``,
    default 1) — always at a line boundary — and the stream starts
    fresh; stale generations beyond ``keep`` are pruned on write, the
    same retention discipline as runs/ (util/runlog.py). Rotation
    failures are swallowed like every other sink failure: observability
    must not fail the workflow."""

    def __init__(self, path: str | None = None, stream: io.IOBase | None = None,
                 max_bytes: int | None = None, keep: int | None = None):
        self._path = path
        self._stream = stream
        self._max_bytes = (
            _max_bytes_from_env() if max_bytes is None else int(max_bytes)
        )
        self._keep = max(1, _keep_from_env() if keep is None else int(keep))
        self._lock = threading.Lock()

    def _maybe_rotate(self, incoming: int) -> None:
        if self._max_bytes <= 0 or self._path is None:
            return
        try:
            if os.path.getsize(self._path) + incoming > self._max_bytes:
                # prune-on-write: a keep lowered between runs retires
                # generations the old setting left behind
                i = self._keep + 1
                while os.path.exists(f"{self._path}.{i}"):
                    os.remove(f"{self._path}.{i}")
                    i += 1
                # cascade oldest-first so every survivor shifts one slot
                for i in range(self._keep, 1, -1):
                    older = f"{self._path}.{i - 1}"
                    if os.path.exists(older):
                        os.replace(older, f"{self._path}.{i}")
                os.replace(self._path, f"{self._path}.1")
        except OSError:
            pass  # no file yet, or rename refused — keep appending

    def write(self, event: dict) -> None:
        line = json.dumps(event, sort_keys=True, default=str)
        with self._lock:
            if self._stream is not None:
                self._stream.write(line + "\n")
                self._stream.flush()
            elif self._path is not None:
                self._maybe_rotate(len(line) + 1)
                with open(self._path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")


_sink: EventSink | None = None
_sink_lock = threading.Lock()
_env_checked = False


def configure(path: str | None = None, stream=None) -> None:
    """Install (or with no arguments, remove) the process event sink."""
    global _sink, _env_checked
    with _sink_lock:
        _env_checked = True  # explicit configure overrides the env default
        _sink = (
            EventSink(path=path, stream=stream)
            if path or stream is not None else None
        )


def _active_sink() -> EventSink | None:
    global _sink, _env_checked
    if not _env_checked:
        with _sink_lock:
            if not _env_checked:
                path = os.environ.get("TPU_K8S_EVENTS")
                if path:
                    _sink = EventSink(path=path)
                _env_checked = True
    return _sink


def emit(kind: str, **fields) -> None:
    """Write one event; a no-op without a sink, and never raises."""
    sink = _active_sink()
    if sink is None:
        return
    event = {"ts": round(time.time(), 6), "kind": kind}
    run = _run_id.get()
    if run:
        event["run"] = run
    parent = _parent_span.get()
    if parent:
        event.setdefault("span", parent)
    event.update(fields)
    try:
        sink.write(event)
    except Exception:  # noqa: BLE001 — observability must not fail the caller
        pass


@contextlib.contextmanager
def run_context(run_id: str | None = None):
    """Scope a run/correlation id (new one when not given) over a block;
    every event and span inside carries it. Yields the id."""
    rid = run_id or new_id()
    token = _run_id.set(rid)
    try:
        yield rid
    finally:
        _run_id.reset(token)


@contextlib.contextmanager
def parent_scope(span_id: str):
    """Make ``span_id`` the parent for spans/events opened inside the
    block — for callers (util/trace.py) that manage their own span
    records but want their nesting visible here."""
    token = _parent_span.set(span_id)
    try:
        yield
    finally:
        _parent_span.reset(token)


@contextlib.contextmanager
def span(name: str, **meta):
    """A nested span: emits span_start/span_end events sharing one span
    id, with the enclosing span as ``parent``. Yields the span id (which
    becomes the parent for anything opened inside the block)."""
    sid = new_id()
    parent = _parent_span.get()
    start = time.monotonic()
    emit("span_start", span=sid, parent=parent, name=name, **meta)
    token = _parent_span.set(sid)
    try:
        yield sid
    except BaseException:
        _parent_span.reset(token)
        emit(
            "span_end", span=sid, parent=parent, name=name,
            seconds=round(time.monotonic() - start, 6), status="error", **meta,
        )
        raise
    else:
        _parent_span.reset(token)
        emit(
            "span_end", span=sid, parent=parent, name=name,
            seconds=round(time.monotonic() - start, 6), status="ok", **meta,
        )
