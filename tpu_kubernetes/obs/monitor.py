"""The ``tpu-kubernetes monitor`` loop: fleet table + firing SLO alerts.

Ties the fleet layer together for an operator terminal: poll the
aggregator (obs/aggregate.py), feed every scrape into the history store
(obs/tsdb.py) and the SLO trackers (obs/slo.py, burn windows read from
the same store), and render one line per worker — RPS, latency
quantiles, TTFT, tokens/sec, in-flight queue depth, ``up`` — plus
unicode sparkline trend columns (RPS, p99, goodput, free KV pages) over
``--window`` seconds and whatever alerts are pending or firing.
``--json`` emits the same snapshot as one JSON object per cycle (what
scripts and the acceptance tests consume); ``--once`` does a single
cycle and exits.

Rates come from the history store. A ``--once`` run that starts with an
empty store takes one short-spaced second scrape so even one-shot
invocations show real RPS/tokens-per-sec instead of ``-``; a store that
already has samples (a long-lived caller, tests) answers immediately.

``run_history`` backs the ``get history <metric>`` CLI: a few spaced
scrapes into a fresh store, then per-series latest/rate/min/max plus a
sparkline — the trends a fleet controller will scale on, on demand.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Callable, TextIO

from tpu_kubernetes.obs.aggregate import FleetAggregator, FleetSnapshot, rate
from tpu_kubernetes.obs.slo import Alert, SLOTracker, default_slos
from tpu_kubernetes.obs.tsdb import TSDB, sparkline

REQUESTS = "tpu_serve_requests_total"
LATENCY = "tpu_serve_request_seconds"
TTFT = "tpu_serve_time_to_first_token_seconds"
TOKENS = "tpu_serve_tokens_generated_total"
TOKENS_CLASS = "tpu_serve_tokens_total"
TOKENS_EMITTED = "tpu_serve_tokens_emitted_total"
INFLIGHT = "tpu_serve_inflight_requests"
KV_FREE_PAGES = "tpu_serve_kv_pages"
BUILD_INFO = "tpu_k8s_build_info"
ROLE_INFO = "tpu_serve_role_info"
SATURATION = "tpu_serve_saturation"
SPEC_DRAFTED_M = "tpu_serve_spec_drafted_total"
SPEC_ACCEPTED_M = "tpu_serve_spec_accepted_total"

# how many slots each sparkline column renders (one char per slot)
SPARK_BINS = 8
# the gap before a --once second scrape when the store starts empty —
# long enough for real counter deltas, short enough for an interactive
# one-shot
ONCE_RESCRAPE_GAP_S = 0.5


def _of_instance(instance: str) -> Callable[[dict[str, str]], bool]:
    return lambda labels: labels.get("instance") == instance


def _trend(store: TSDB, instance: str, window: float, now: float,
           ) -> dict[str, list[float | None]]:
    """The per-instance sparkline feeds, oldest bin first."""
    mine = _of_instance(instance)
    bins = SPARK_BINS
    # p99 per bin: the windowed quantile evaluated at each bin's right
    # edge over a bin-sized sub-window
    width = window / bins
    p99: list[float | None] = []
    for i in range(bins):
        edge = now - window + (i + 1) * width
        p99.append(store.quantile_over_time(LATENCY, 0.99, width, edge, mine))
    emitted = store.binned(TOKENS_EMITTED, window, now, bins, "rate", mine)
    useful = store.binned(
        TOKENS_CLASS, window, now, bins, "rate",
        lambda labels: (labels.get("instance") == instance
                        and labels.get("class") == "useful"),
    )
    goodput: list[float | None] = [
        (u / e) if (u is not None and e not in (None, 0.0)) else None
        for u, e in zip(useful, emitted)
    ]
    return {
        "rps": store.binned(REQUESTS, window, now, bins, "rate", mine),
        "p99_s": p99,
        "goodput": goodput,
        "free_pages": store.binned(KV_FREE_PAGES, window, now, bins,
                                   "value", mine),
    }


def fleet_rows(snapshot: FleetSnapshot,
               prev: FleetSnapshot | None = None,
               store: TSDB | None = None,
               window: float = 60.0) -> list[dict[str, Any]]:
    """Per-instance stats rows. With a history ``store`` the rate and
    trend columns come from it (reset-aware, any number of retained
    cycles); ``prev`` (the previous cycle's snapshot) is the fallback
    two-point rate for store-less callers."""
    rows = []
    dt = snapshot.ts - prev.ts if prev is not None else 0.0
    for instance in snapshot.instances():
        health = snapshot.health[instance]
        mine = _of_instance(instance)
        requests = snapshot.value_sum(REQUESTS, mine)
        tokens = snapshot.value_sum(TOKENS, mine)
        # goodput: the ledger's useful share of every token the device
        # produced (obs/ledger.py conservation classes) — None until the
        # worker has emitted anything
        emitted = snapshot.value_sum(TOKENS_EMITTED, mine)
        useful = snapshot.value_sum(
            TOKENS_CLASS,
            lambda labels: (labels.get("instance") == instance
                            and labels.get("class") == "useful"),
        )
        row: dict[str, Any] = {
            "instance": instance,
            "up": health.up,
            # lifecycle from the healthz probe (serving/warming/
            # draining/failed; None when probing is off or the target
            # has no healthz) — distinguishes a draining instance from
            # a merely saturated one
            "state": health.lifecycle or None,
            # per-instance build version (tpu_k8s_build_info) — a mixed
            # column during a rollout is the point of carrying it here
            "version": snapshot.label_value(BUILD_INFO, "version", mine),
            # the worker's SERVE_ROLE tier and the aggregator's
            # saturation score — what a disagg-aware balancer reads
            "role": snapshot.label_value(ROLE_INFO, "role", mine),
            "saturation": next(
                (s.value
                 for s in snapshot._samples(SATURATION, SATURATION, mine)),
                None,
            ),
            "consecutive_failures": health.consecutive_failures,
            "scrape_seconds": health.last_scrape_seconds,
            "error": health.last_error,
            "requests_total": requests,
            "tokens_total": tokens,
            "rps": None,
            "tokens_per_s": None,
            "p50_s": snapshot.quantile(LATENCY, 0.50, mine),
            "p99_s": snapshot.quantile(LATENCY, 0.99, mine),
            "ttft_p99_s": snapshot.quantile(TTFT, 0.99, mine),
            "queue_depth": snapshot.value_sum(INFLIGHT, mine),
            "goodput": round(useful / emitted, 4) if emitted else None,
        }
        # speculative acceptance rate (accepted/drafted over both
        # proposer sources) — None for workers that never drafted, so
        # the column only lights up on speculating instances
        drafted = snapshot.value_sum(SPEC_DRAFTED_M, mine)
        accepted = snapshot.value_sum(SPEC_ACCEPTED_M, mine)
        row["spec_accept"] = (
            round(accepted / drafted, 4) if drafted else None
        )
        if store is not None:
            row["rps"] = store.rate_over_time(
                REQUESTS, window, snapshot.ts, mine
            )
            row["tokens_per_s"] = store.rate_over_time(
                TOKENS, window, snapshot.ts, mine
            )
            trend = _trend(store, instance, window, snapshot.ts)
            row["trend"] = trend
            row["spark"] = {k: sparkline(v) for k, v in trend.items()}
        elif prev is not None and instance in prev.health:
            row["rps"] = rate(
                requests, prev.value_sum(REQUESTS, mine), dt
            )
            row["tokens_per_s"] = rate(
                tokens, prev.value_sum(TOKENS, mine), dt
            )
        rows.append(row)
    return rows


def _fmt(value: Any, unit: str = "", width: int = 8) -> str:
    if value is None:
        return "-".rjust(width)
    if isinstance(value, float):
        text = f"{value:.3f}{unit}" if abs(value) < 100 else f"{value:.0f}{unit}"
    else:
        text = f"{value}{unit}"
    return text.rjust(width)


def render_table(rows: list[dict[str, Any]], alerts: list[Alert],
                 ts: float | None = None,
                 rule_alerts: list[dict[str, Any]] | None = None) -> str:
    """The human rendering: one aligned row per instance (trend columns
    when the rows carry history sparklines), then any pending/firing
    alerts — the SLO trackers' burn alerts plus, when an alert manager
    runs, its rule alerts (``rule_alerts``; slo_burn rules are skipped
    there since the tracker rows already show them)."""
    with_trends = any("spark" in row for row in rows)
    header = (
        f"{'INSTANCE':<24} {'UP':>2} {'VER':>8} {'ROLE':>8} {'STATE':>9} "
        f"{'RPS':>8} "
        f"{'P50':>8} {'P99':>8} {'TTFT99':>8} {'TOK/S':>8} {'QUEUE':>6} "
        f"{'SAT':>6} {'GOODPUT':>8} {'SPEC%':>6}"
    )
    if with_trends:
        header += (
            f"  {'~RPS':<8} {'~P99':<8} {'~GOODPUT':<8} {'~FREEPG':<8}"
        )
    lines = []
    if ts is not None:
        lines.append(time.strftime(
            "fleet @ %Y-%m-%d %H:%M:%S", time.localtime(ts)
        ))
    lines.append(header)
    for row in rows:
        line = (
            f"{row['instance']:<24} {row['up']:>2}"
            f" {(row.get('version') or '-'):>8}"
            f" {(row.get('role') or '-'):>8}"
            f" {(row.get('state') or '-'):>9}"
            f"{_fmt(row['rps'])}"
            f"{_fmt(row['p50_s'], 's', 9)}"
            f"{_fmt(row['p99_s'], 's', 9)}"
            f"{_fmt(row['ttft_p99_s'], 's', 9)}"
            f"{_fmt(row['tokens_per_s'])}"
            f"{_fmt(int(row['queue_depth']), '', 7)}"
            f"{_fmt(row.get('saturation'), '', 7)}"
            f"{_fmt(row.get('goodput'), '', 9)}"
            f"{_fmt(row.get('spec_accept'), '', 7)}"
        )
        if with_trends:
            spark = row.get("spark", {})
            line += (
                f"  {spark.get('rps', ''):<8} {spark.get('p99_s', ''):<8}"
                f" {spark.get('goodput', ''):<8}"
                f" {spark.get('free_pages', ''):<8}"
            )
        lines.append(line)
        if not row["up"] and row["error"]:
            lines.append(
                f"  └─ down ({row['consecutive_failures']} consecutive): "
                f"{row['error']}"
            )
    active = [a for a in alerts if a.state != "ok"]
    # the manager's non-SLO rule alerts (tripwires, anomaly detectors);
    # slo_burn entries would duplicate the tracker rows above
    extra = [
        a for a in (rule_alerts or [])
        if a.get("state") not in ("ok",) and a.get("kind") != "slo_burn"
    ]
    if active or extra:
        lines.append("")
        lines.append("ALERTS")
        for a in active:
            age = f" for {a.age_s:.0f}s" if a.age_s is not None else ""
            lines.append(
                f"  [{a.state.upper():>7}] {a.slo} (target {a.target:.3%})"
                f" burn fast={a.burn_fast:.1f}x slow={a.burn_slow:.1f}x{age}"
                f"{' — ' + a.description if a.description else ''}"
            )
        for a in extra:
            labels = ",".join(
                f"{k}={v}" for k, v in sorted(a.get("labels", {}).items())
            )
            age = (f" for {a['age_s']:.0f}s"
                   if a.get("age_s") is not None else "")
            lines.append(
                f"  [{a.get('state', '?').upper():>7}] {a.get('rule')}"
                f"{'{' + labels + '}' if labels else ''}"
                f" severity={a.get('severity') or '-'}{age}"
                f"{' — ' + a['summary'] if a.get('summary') else ''}"
                f"{' (silenced)' if a.get('silenced') else ''}"
            )
    return "\n".join(lines) + "\n"


def snapshot_json(snapshot: FleetSnapshot, rows: list[dict[str, Any]],
                  alerts: list[Alert],
                  rule_alerts: list[dict[str, Any]] | None = None,
                  ) -> dict[str, Any]:
    """One cycle as a JSON-ready object (``monitor --json``). The
    ``alerts`` key keeps the historical tracker-alert shape;
    ``rule_alerts`` (when an alert manager runs) carries the manager's
    fingerprinted view of everything, SLO burn included."""
    out = {
        "ts": snapshot.ts,
        "instances": {row["instance"]: row for row in rows},
        "alerts": [a.to_dict() for a in alerts],
    }
    if rule_alerts is not None:
        out["rule_alerts"] = rule_alerts
    return out


def run_monitor(targets: list[str], interval: float = 5.0,
                once: bool = False, as_json: bool = False,
                out: TextIO | None = None,
                slos: list[SLOTracker] | None = None,
                max_cycles: int | None = None,
                timeout_s: float = 2.0,
                window: float = 60.0,
                store: TSDB | None = None,
                alert_manager=None) -> int:
    """The CLI loop. Returns the process exit code. ``store`` lets a
    caller pre-seed (or retain) fleet history across invocations; by
    default each run owns a fresh one. ``alert_manager`` takes a
    pre-built :class:`~tpu_kubernetes.obs.alerts.AlertManager`; by
    default the loop builds one from the SLO trackers plus the standard
    fleet rules (target-down, restart delta, latency drift, counter
    stall, queue runaway), env-configured sinks, and any
    ``TPU_K8S_ALERTS_D`` rule files — evaluated every scrape cycle."""
    out = sys.stdout if out is None else out
    store = TSDB() if store is None else store
    # the poll interval doubles as the backoff base: a dead target falls
    # back to ~8x interval re-polls instead of burning a timeout per
    # cycle forever (one-shot runs keep every target in the cycle)
    aggregator = FleetAggregator(
        targets, timeout_s=timeout_s,
        backoff_base_s=0.0 if once else interval,
        tsdb=store,
        # the STATE column: one healthz probe per target per cycle
        probe_health=True,
    )
    trackers = default_slos(store=store) if slos is None else slos
    manager = alert_manager
    owns_manager = manager is None
    if owns_manager:
        from tpu_kubernetes.obs import alerts as alerts_mod

        rules = alerts_mod.default_fleet_rules(trackers)
        rules_d = os.environ.get("TPU_K8S_ALERTS_D", "")
        if rules_d:
            try:
                rules += alerts_mod.load_rules(rules_d)
            except Exception as e:  # noqa: BLE001 — a bad rule file is
                print(f"warning: TPU_K8S_ALERTS_D: {e}",  # operator error,
                      file=sys.stderr)                    # not a crash
        manager = alerts_mod.AlertManager(
            rules, sinks=alerts_mod.sinks_from_env(),
            group_interval_s=float(
                os.environ.get("TPU_K8S_ALERT_GROUP_S", "60") or 60
            ),
        )
    cycles = 0
    try:
        while True:
            snapshot = aggregator.scrape_once()
            if once and cycles == 0:
                # one-shot runs against a cold store can't answer rates
                # (one point per counter) — a second short-spaced scrape
                # seeds real deltas; a pre-seeded store (a long-lived
                # caller handed history in) answers immediately
                needs_seed = store.has_samples(REQUESTS) and all(
                    len(samples) < 2
                    for _, samples in store.window(
                        REQUESTS, snapshot.ts - window, snapshot.ts
                    )
                )
                if needs_seed:
                    time.sleep(ONCE_RESCRAPE_GAP_S)
                    snapshot = aggregator.scrape_once()
            for tracker in trackers:
                tracker.observe(snapshot, now=snapshot.ts)
            alerts = [t.evaluate(now=snapshot.ts) for t in trackers]
            # the manager's SLOBurnRule re-evaluates the same trackers at
            # the same `now` — the state machine is idempotent per instant
            rule_alerts = manager.evaluate(
                snapshot=snapshot, store=store, now=snapshot.ts
            )
            rows = fleet_rows(snapshot, store=store, window=window)
            if as_json:
                print(json.dumps(
                    snapshot_json(snapshot, rows, alerts,
                                  rule_alerts=rule_alerts),
                    sort_keys=True), file=out, flush=True)
            else:
                print(render_table(rows, alerts, ts=snapshot.ts,
                                   rule_alerts=rule_alerts),
                      file=out, flush=True)
            cycles += 1
            if once or (max_cycles is not None and cycles >= max_cycles):
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if owns_manager:
            manager.close()


def run_history(metric: str, targets: list[str], window: float = 60.0,
                samples: int = 5, interval: float = 1.0,
                as_json: bool = False, out: TextIO | None = None,
                timeout_s: float = 2.0,
                store: TSDB | None = None) -> int:
    """``get history <metric>``: scrape a few spaced cycles into a
    history store (or query one handed in), then render every series of
    the metric — latest, per-second rate (counters), min/max, sparkline.
    Exit 1 when the metric never appeared (typo or all targets down)."""
    out = sys.stdout if out is None else out
    scraped_here = store is None
    store = TSDB() if store is None else store
    aggregator = FleetAggregator(targets, timeout_s=timeout_s, tsdb=store)
    cycles = max(2, int(samples)) if scraped_here else max(1, int(samples))
    for i in range(cycles):
        snapshot = aggregator.scrape_once()
        if i < cycles - 1:
            time.sleep(max(0.0, interval))
    now = snapshot.ts
    series = store.window(metric, now - window, now)
    payload = {
        "metric": metric,
        "window_s": window,
        "ts": now,
        "series": [],
    }
    for labels, points in sorted(series, key=lambda kv: sorted(kv[0].items())):
        mine = (lambda want: lambda have: all(
            have.get(k) == v for k, v in want.items()
        ))(labels)
        vals = [v for _, v in points]
        entry = {
            "labels": labels,
            "latest": vals[-1] if vals else None,
            "min": min(vals) if vals else None,
            "max": max(vals) if vals else None,
            "rate_per_s": store.rate_over_time(metric, window, now, mine),
            "spark": sparkline(
                store.binned(metric, window, now, SPARK_BINS, "rate", mine)
                if len(points) >= 2 else
                store.binned(metric, window, now, SPARK_BINS, "value", mine)
            ),
            "samples": [[round(t, 3), v] for t, v in points],
        }
        payload["series"].append(entry)
    if as_json:
        print(json.dumps(payload, sort_keys=True), file=out, flush=True)
    elif not payload["series"]:
        print(f"no samples for {metric!r} (targets down or unknown metric; "
              f"try `get metrics` for names)", file=out, flush=True)
    else:
        print(f"{metric} over the last {window:g}s "
              f"({len(payload['series'])} series)", file=out, flush=True)
        for entry in payload["series"]:
            labels = ",".join(
                f"{k}={v}" for k, v in sorted(entry["labels"].items())
            )
            print(
                f"  {{{labels}}}"
                f" latest={_fmt(entry['latest']).strip()}"
                f" rate/s={_fmt(entry['rate_per_s']).strip()}"
                f" min={_fmt(entry['min']).strip()}"
                f" max={_fmt(entry['max']).strip()}"
                f"  {entry['spark']}",
                file=out, flush=True,
            )
    return 0 if payload["series"] else 1
