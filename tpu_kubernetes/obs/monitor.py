"""The ``tpu-kubernetes monitor`` loop: fleet table + firing SLO alerts.

Ties the fleet layer together for an operator terminal: poll the
aggregator (obs/aggregate.py), feed the SLO trackers (obs/slo.py), and
render one line per worker — RPS, latency quantiles, TTFT, tokens/sec,
in-flight queue depth, and ``up`` — plus whatever alerts are pending or
firing. ``--json`` emits the same snapshot as one JSON object per cycle
(what scripts and the acceptance tests consume); ``--once`` does a
single cycle and exits.

Rates (RPS, tokens/sec) are deltas between consecutive cycles, so the
first cycle — and every ``--once`` run — shows ``-`` for them; quantiles
come from the cumulative histograms (since worker start).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, TextIO

from tpu_kubernetes.obs.aggregate import FleetAggregator, FleetSnapshot, rate
from tpu_kubernetes.obs.slo import Alert, SLOTracker, default_slos

REQUESTS = "tpu_serve_requests_total"
LATENCY = "tpu_serve_request_seconds"
TTFT = "tpu_serve_time_to_first_token_seconds"
TOKENS = "tpu_serve_tokens_generated_total"
TOKENS_CLASS = "tpu_serve_tokens_total"
TOKENS_EMITTED = "tpu_serve_tokens_emitted_total"
INFLIGHT = "tpu_serve_inflight_requests"
BUILD_INFO = "tpu_k8s_build_info"


def _of_instance(instance: str) -> Callable[[dict[str, str]], bool]:
    return lambda labels: labels.get("instance") == instance


def fleet_rows(snapshot: FleetSnapshot,
               prev: FleetSnapshot | None = None) -> list[dict[str, Any]]:
    """Per-instance stats rows. ``prev`` (the previous cycle's snapshot)
    enables the rate columns; without it they are None."""
    rows = []
    dt = snapshot.ts - prev.ts if prev is not None else 0.0
    for instance in snapshot.instances():
        health = snapshot.health[instance]
        mine = _of_instance(instance)
        requests = snapshot.value_sum(REQUESTS, mine)
        tokens = snapshot.value_sum(TOKENS, mine)
        # goodput: the ledger's useful share of every token the device
        # produced (obs/ledger.py conservation classes) — None until the
        # worker has emitted anything
        emitted = snapshot.value_sum(TOKENS_EMITTED, mine)
        useful = snapshot.value_sum(
            TOKENS_CLASS,
            lambda labels: (labels.get("instance") == instance
                            and labels.get("class") == "useful"),
        )
        row: dict[str, Any] = {
            "instance": instance,
            "up": health.up,
            # per-instance build version (tpu_k8s_build_info) — a mixed
            # column during a rollout is the point of carrying it here
            "version": snapshot.label_value(BUILD_INFO, "version", mine),
            "consecutive_failures": health.consecutive_failures,
            "scrape_seconds": health.last_scrape_seconds,
            "error": health.last_error,
            "requests_total": requests,
            "tokens_total": tokens,
            "rps": None,
            "tokens_per_s": None,
            "p50_s": snapshot.quantile(LATENCY, 0.50, mine),
            "p99_s": snapshot.quantile(LATENCY, 0.99, mine),
            "ttft_p99_s": snapshot.quantile(TTFT, 0.99, mine),
            "queue_depth": snapshot.value_sum(INFLIGHT, mine),
            "goodput": round(useful / emitted, 4) if emitted else None,
        }
        if prev is not None and instance in prev.health:
            row["rps"] = rate(
                requests, prev.value_sum(REQUESTS, mine), dt
            )
            row["tokens_per_s"] = rate(
                tokens, prev.value_sum(TOKENS, mine), dt
            )
        rows.append(row)
    return rows


def _fmt(value: Any, unit: str = "", width: int = 8) -> str:
    if value is None:
        return "-".rjust(width)
    if isinstance(value, float):
        text = f"{value:.3f}{unit}" if abs(value) < 100 else f"{value:.0f}{unit}"
    else:
        text = f"{value}{unit}"
    return text.rjust(width)


def render_table(rows: list[dict[str, Any]], alerts: list[Alert],
                 ts: float | None = None) -> str:
    """The human rendering: one aligned row per instance, then any
    pending/firing alerts."""
    header = (
        f"{'INSTANCE':<24} {'UP':>2} {'VER':>8} {'RPS':>8} {'P50':>8} "
        f"{'P99':>8} {'TTFT99':>8} {'TOK/S':>8} {'QUEUE':>6} {'GOODPUT':>8}"
    )
    lines = []
    if ts is not None:
        lines.append(time.strftime(
            "fleet @ %Y-%m-%d %H:%M:%S", time.localtime(ts)
        ))
    lines.append(header)
    for row in rows:
        lines.append(
            f"{row['instance']:<24} {row['up']:>2}"
            f" {(row.get('version') or '-'):>8}"
            f"{_fmt(row['rps'])}"
            f"{_fmt(row['p50_s'], 's', 9)}"
            f"{_fmt(row['p99_s'], 's', 9)}"
            f"{_fmt(row['ttft_p99_s'], 's', 9)}"
            f"{_fmt(row['tokens_per_s'])}"
            f"{_fmt(int(row['queue_depth']), '', 7)}"
            f"{_fmt(row.get('goodput'), '', 9)}"
        )
        if not row["up"] and row["error"]:
            lines.append(
                f"  └─ down ({row['consecutive_failures']} consecutive): "
                f"{row['error']}"
            )
    active = [a for a in alerts if a.state != "ok"]
    if active:
        lines.append("")
        lines.append("ALERTS")
        for a in active:
            lines.append(
                f"  [{a.state.upper():>7}] {a.slo} (target {a.target:.3%})"
                f" burn fast={a.burn_fast:.1f}x slow={a.burn_slow:.1f}x"
                f"{' — ' + a.description if a.description else ''}"
            )
    return "\n".join(lines) + "\n"


def snapshot_json(snapshot: FleetSnapshot, rows: list[dict[str, Any]],
                  alerts: list[Alert]) -> dict[str, Any]:
    """One cycle as a JSON-ready object (``monitor --json``)."""
    return {
        "ts": snapshot.ts,
        "instances": {row["instance"]: row for row in rows},
        "alerts": [a.to_dict() for a in alerts],
    }


def run_monitor(targets: list[str], interval: float = 5.0,
                once: bool = False, as_json: bool = False,
                out: TextIO | None = None,
                slos: list[SLOTracker] | None = None,
                max_cycles: int | None = None,
                timeout_s: float = 2.0) -> int:
    """The CLI loop. Returns the process exit code."""
    out = sys.stdout if out is None else out
    # the poll interval doubles as the backoff base: a dead target falls
    # back to ~8x interval re-polls instead of burning a timeout per
    # cycle forever (one-shot runs keep every target in the cycle)
    aggregator = FleetAggregator(
        targets, timeout_s=timeout_s,
        backoff_base_s=0.0 if once else interval,
    )
    trackers = default_slos() if slos is None else slos
    prev: FleetSnapshot | None = None
    cycles = 0
    try:
        while True:
            snapshot = aggregator.scrape_once()
            for tracker in trackers:
                tracker.observe(snapshot, now=snapshot.ts)
            alerts = [t.evaluate(now=snapshot.ts) for t in trackers]
            rows = fleet_rows(snapshot, prev)
            if as_json:
                print(json.dumps(snapshot_json(snapshot, rows, alerts),
                                 sort_keys=True), file=out, flush=True)
            else:
                print(render_table(rows, alerts, ts=snapshot.ts),
                      file=out, flush=True)
            prev = snapshot
            cycles += 1
            if once or (max_cycles is not None and cycles >= max_cycles):
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
