"""Prometheus text-exposition parser (and its inverse renderer).

obs/metrics.py renders the process registry as text exposition version
0.0.4; this module is the other direction — what a scraper needs to turn
a worker's ``GET /metrics`` body back into structured samples so the
fleet aggregator (obs/aggregate.py) can merge many workers into one
view. Same stance as the rest of obs/: stdlib only, the scrape path must
stay air-gap friendly.

The grammar handled is exactly what our emitter produces (``# HELP`` /
``# TYPE`` headers followed by ``name{label="value"} number`` samples,
histograms as ``_bucket``/``_sum``/``_count`` rows), tolerating other
comment lines and untyped samples from foreign exporters. The contract
tests lean on: ``render(parse(text)) == text`` byte-for-byte for any
registry exposition — label escaping, ``+Inf`` bounds, and the empty
registry included — so scraped numbers re-expose losslessly.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace


class ParseError(ValueError):
    """A line the exposition grammar cannot account for."""


@dataclass
class Exemplar:
    """An OpenMetrics exemplar riding a sample line (``# {…} value``):
    the trace-id labels and the observed value that landed in that
    bucket — how a p99 bucket points at a real slow trace."""

    labels: tuple[tuple[str, str], ...]
    value: float


@dataclass
class Sample:
    """One exposed time series value. ``name`` is the full sample name
    (``foo_bucket``, ``foo_sum``, … for histogram rows); ``labels`` keeps
    the rendered pair order so re-emission is byte-identical. An
    exemplar, when present, survives parse → merge → render untouched
    (``with_label`` copies carry it via ``replace``)."""

    name: str
    labels: tuple[tuple[str, str], ...]
    value: float
    exemplar: Exemplar | None = None

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)

    def with_label(self, key: str, value: str) -> "Sample":
        """A copy with one more label appended (how the aggregator tags
        scraped samples with their ``instance``)."""
        return replace(self, labels=(*self.labels, (key, str(value))))


@dataclass
class Family:
    """One metric family as exposed: header lines plus its samples in
    file order."""

    name: str
    help: str = ""
    kind: str = "untyped"
    samples: list[Sample] = field(default_factory=list)


def _unescape_label(raw: str) -> str:
    out: list[str] = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:  # unknown escape: keep verbatim (foreign exporter)
                out.append(c + nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    try:
        return float(raw)
    except ValueError as e:
        raise ParseError(f"unparseable sample value {raw!r}") from e


def format_value(v: float) -> str:
    """The emitter's number formatting (obs/metrics.py) — integers bare,
    floats via repr (which round-trips exactly), infinities spelled the
    Prometheus way — so a parsed value re-renders to the same bytes."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _parse_labels(raw: str, line: str) -> tuple[tuple[str, str], ...]:
    """``key="value",…`` (the part between braces) → ordered pairs."""
    pairs: list[tuple[str, str]] = []
    i = 0
    while i < len(raw):
        eq = raw.find("=", i)
        if eq < 0 or eq + 1 >= len(raw) or raw[eq + 1] != '"':
            raise ParseError(f"malformed labels in line {line!r}")
        key = raw[i:eq].strip()
        # scan the quoted value, honoring backslash escapes
        j = eq + 2
        buf: list[str] = []
        while j < len(raw):
            c = raw[j]
            if c == "\\" and j + 1 < len(raw):
                buf.append(raw[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        else:
            raise ParseError(f"unterminated label value in line {line!r}")
        pairs.append((key, _unescape_label("".join(buf))))
        i = j + 1
        if i < len(raw) and raw[i] == ",":
            i += 1
    return tuple(pairs)


def _split_exemplar(line: str) -> tuple[str, str]:
    """Split an OpenMetrics exemplar suffix (`` # {…} value``) off a
    sample line, honoring quotes — a label *value* containing the
    marker must not trigger the split. Returns (body, raw_exemplar);
    raw_exemplar is "" when the line carries none."""
    in_quotes = False
    i = 0
    while i < len(line):
        c = line[i]
        if in_quotes:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_quotes = False
        elif c == '"':
            in_quotes = True
        elif c == "#" and line.startswith(" # {", i - 1):
            return line[:i - 1], line[i + 2:]
        i += 1
    return line, ""


def _parse_exemplar(raw: str, line: str) -> Exemplar:
    """``{labels} value [timestamp]`` → :class:`Exemplar`."""
    if not raw.startswith("{"):
        raise ParseError(f"malformed exemplar in line {line!r}")
    # quote-aware scan for the closing brace
    in_quotes = False
    j = 1
    while j < len(raw):
        c = raw[j]
        if in_quotes:
            if c == "\\":
                j += 2
                continue
            if c == '"':
                in_quotes = False
        elif c == '"':
            in_quotes = True
        elif c == "}":
            break
        j += 1
    else:
        raise ParseError(f"unterminated exemplar in line {line!r}")
    labels = _parse_labels(raw[1:j], line)
    rest = raw[j + 1:].strip()
    if not rest:
        raise ParseError(f"exemplar missing value in line {line!r}")
    return Exemplar(labels=labels, value=parse_value(rest.split(" ")[0]))


def _base_name(sample_name: str, families: dict[str, Family]) -> str:
    """Histogram rows are exposed under ``<family>_bucket/_sum/_count``;
    map a sample name back to the family that declared it."""
    if sample_name in families:
        return sample_name
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in families:
                return base
    return sample_name


def parse(text: str) -> list[Family]:
    """Exposition text → families in file order. Raises
    :class:`ParseError` on lines that are neither comments nor samples."""
    families: dict[str, Family] = {}
    order: list[str] = []

    def family(name: str) -> Family:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = Family(name=name)
            order.append(name)
        return fam

    for line in text.split("\n"):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 3 and parts[1] == "HELP":
                family(parts[2]).help = parts[3] if len(parts) > 3 else ""
            elif len(parts) >= 3 and parts[1] == "TYPE":
                family(parts[2]).kind = (
                    parts[3].strip() if len(parts) > 3 else "untyped"
                )
            # other comments (OpenMetrics `# EOF` included) are legal
            # exposition — ignored
            continue
        # an OpenMetrics exemplar suffix must come off before the
        # rfind("}") below — its braces would corrupt the label scan
        body, raw_exemplar = _split_exemplar(line)
        exemplar = _parse_exemplar(raw_exemplar, line) if raw_exemplar \
            else None
        brace = body.find("{")
        if brace >= 0:
            close = body.rfind("}")
            if close < brace:
                raise ParseError(f"malformed sample line {line!r}")
            name = body[:brace]
            labels = _parse_labels(body[brace + 1:close], line)
            rest = body[close + 1:].strip()
        else:
            name, _, rest = body.partition(" ")
            labels = ()
            rest = rest.strip()
        if not name or not rest:
            raise ParseError(f"malformed sample line {line!r}")
        value = parse_value(rest.split(" ")[0])  # a timestamp may follow
        family(_base_name(name, families)).samples.append(
            Sample(name=name, labels=labels, value=value,
                   exemplar=exemplar)
        )
    return [families[n] for n in order]


def render_sample(sample: Sample) -> str:
    labels = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sample.labels
    )
    body = "{" + labels + "}" if labels else ""
    line = f"{sample.name}{body} {format_value(sample.value)}"
    if sample.exemplar is not None:
        ex_labels = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in sample.exemplar.labels
        )
        line += " # {" + ex_labels + "} " + format_value(sample.exemplar.value)
    return line


def render(families: list[Family]) -> str:
    """Families → exposition text, the exact inverse of :func:`parse`
    over anything obs/metrics.py emits (the round-trip contract)."""
    lines: list[str] = []
    for fam in families:
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for sample in fam.samples:
            lines.append(render_sample(sample))
    return "\n".join(lines) + "\n" if lines else ""


def bucket_quantile(buckets: list[tuple[float, float]],
                    q: float) -> float | None:
    """Estimate quantile ``q`` from cumulative ``(le, count)`` pairs —
    Prometheus ``histogram_quantile`` semantics: linear interpolation
    inside the bucket holding the rank; the ``+Inf`` bucket answers with
    the highest finite bound (never inf/NaN). None when the histogram is
    empty or only a ``+Inf`` bucket exists — with no finite bound at all
    there is no honest estimate to return."""
    if not buckets:
        return None
    buckets = sorted(buckets)
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_n = 0.0, 0.0
    seen_finite = False
    for le, n in buckets:
        if n >= rank:
            if math.isinf(le):
                return prev_le if seen_finite else None
            if n == prev_n:
                return le
            return prev_le + (le - prev_le) * ((rank - prev_n) / (n - prev_n))
        prev_le, prev_n = le, n
        seen_finite = seen_finite or not math.isinf(le)
    return prev_le if seen_finite else None
