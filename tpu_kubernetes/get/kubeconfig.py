"""Build a working kubeconfig for the fleet control plane.

The reference closes its aha loop by minting usable API credentials on the
manager (reference: terraform/modules/files/setup_rancher.sh.tpl:1-50) so a
user can talk to the control plane immediately. Round-2 VERDICT Missing #1:
our README ended in a ``kubectl apply`` the user had no kubeconfig for.

``tpu-kubernetes get kubeconfig`` fixes that: the manager module already
outputs ``api_url`` (public address) and ``secret_key`` (the fleet-admin
ServiceAccount token published by install_manager.sh.tpl), so the kubeconfig
is *synthesized* client-side — no SSH scrape of /etc/rancher/k3s/k3s.yaml,
no server-address rewriting. The cluster CA is fetched from the k3s
``/cacerts`` endpoint (the same trust-bootstrap every joining agent does,
install_node_agent.sh.tpl) and embedded so kubectl verifies TLS from then
on; the CA's sha256 is emitted for cross-checking against the
``ca_checksum`` recorded in every cluster registration.
"""

from __future__ import annotations

import base64
import hashlib
import http.client
import urllib.error
import urllib.request

import yaml


class KubeconfigError(Exception):
    pass


def fetch_ca_pem(api_url: str, timeout_s: float = 15.0) -> bytes:
    """GET <api_url>/cacerts. TLS is unverified here by necessity — this IS
    the trust bootstrap (the agents' ``curl -ks`` analog); the returned CA's
    checksum is surfaced for out-of-band verification."""
    from tpu_kubernetes.util.bootstrap_tls import urlopen_kwargs

    url = api_url.rstrip("/") + "/cacerts"
    kwargs = urlopen_kwargs(url)
    try:
        with urllib.request.urlopen(url, timeout=timeout_s, **kwargs) as resp:
            data = resp.read()
    # ValueError: scheme-less api_url from a hand-edited state doc;
    # HTTPException: garbage status line from a proxy / mid-restart k3s
    except (urllib.error.URLError, OSError, ValueError,
            http.client.HTTPException) as e:
        raise KubeconfigError(
            f"cannot fetch the cluster CA from {url}: {e} — is the manager "
            "up and port 6443 reachable?"
        ) from e
    if not data:
        raise KubeconfigError(f"{url} returned an empty body")
    return data


def build_kubeconfig(
    manager: str, api_url: str, token: str, ca_pem: bytes
) -> str:
    """A self-contained kubeconfig: embedded CA + bearer token."""
    doc = {
        "apiVersion": "v1",
        "kind": "Config",
        "clusters": [{
            "name": manager,
            "cluster": {
                "server": api_url,
                "certificate-authority-data":
                    base64.b64encode(ca_pem).decode(),
            },
        }],
        "users": [{
            "name": f"{manager}-fleet-admin",
            "user": {"token": token},
        }],
        "contexts": [{
            "name": manager,
            "context": {"cluster": manager, "user": f"{manager}-fleet-admin"},
        }],
        "current-context": manager,
    }
    checksum = hashlib.sha256(ca_pem).hexdigest()
    header = (
        f"# kubeconfig for tpu-kubernetes manager {manager!r}\n"
        f"# CA sha256: {checksum} — cross-check against the ca_checksum in\n"
        f"# any cluster registration record (tpu-fleet/cluster-* ConfigMaps)\n"
    )
    return header + yaml.safe_dump(doc, sort_keys=False)
