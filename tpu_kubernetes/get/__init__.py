from tpu_kubernetes.get.workflows import get_cluster, get_manager  # noqa: F401
