from tpu_kubernetes.get.workflows import (  # noqa: F401
    get_cluster,
    get_kubeconfig,
    get_manager,
)
