from tpu_kubernetes.get.workflows import (  # noqa: F401
    format_runs,
    get_cluster,
    get_kubeconfig,
    get_manager,
    get_runs,
)
