"""``get manager|cluster|runs`` workflows: query live outputs and the
recorded run history.

reference: get/manager.go:16-96 and get/cluster.go:17-140 — render the state
to a temp dir, ``terraform init`` + ``terraform output`` for the module of
interest, print the result. ``get runs`` has no reference analog: it reads
the run reports persisted next to the state document (util/runlog.py).
"""

from __future__ import annotations

import time
from typing import Any

from tpu_kubernetes.backend import Backend
from tpu_kubernetes.config import Config
from tpu_kubernetes.create.node import select_cluster, select_manager
from tpu_kubernetes.shell import Executor
from tpu_kubernetes.state import MANAGER_KEY


def get_manager(backend: Backend, cfg: Config, executor: Executor) -> dict[str, Any]:
    """reference: get/manager.go:83-92 — plus the latest run report (phase
    timing breakdown, SURVEY §5.1) and a fleet-wide node summary (Ready
    counts per cluster, one Nodes list) which the reference delegates to
    the Rancher UI."""
    manager = select_manager(backend, cfg)
    state = backend.state(manager)
    out = executor.output(state, MANAGER_KEY)
    last_run = backend.last_run_report(manager)
    if last_run is not None:
        out = {**out, "last_run": last_run}

    api_url, token = out.get("api_url"), out.get("secret_key")
    if api_url and token:
        from tpu_kubernetes.fleet import FleetAPI, list_nodes, node_ready

        # pin the manager CA with any registered cluster's recorded
        # ca_checksum (shared control plane: they all pin the same CA);
        # short timeouts — this is advisory, terraform outputs are the
        # answer the user actually asked for
        ca = None
        cluster_key = next(iter(state.clusters().values()), None)
        if cluster_key:
            try:
                ca = executor.output(state, cluster_key).get("ca_checksum")
            except Exception:  # noqa: BLE001 — pin is best-available
                pass
        try:
            items = list_nodes(FleetAPI(
                str(api_url), str(token),
                ca_checksum=str(ca) if ca else None, timeout_s=5.0,
            ))
        except Exception as e:  # noqa: BLE001 — health is best-effort here
            out = {**out, "fleet_health_error": str(e)[:200]}
        else:
            summary: dict[str, dict[str, int]] = {}
            for item in items:
                labels = (item.get("metadata") or {}).get("labels") or {}
                pool = (
                    "manager" if labels.get("tpu-kubernetes/role") == "manager"
                    else labels.get("tpu-kubernetes/cluster") or "(unlabeled)"
                )
                bucket = summary.setdefault(pool, {"ready": 0, "not_ready": 0})
                bucket["ready" if node_ready(item) else "not_ready"] += 1
            out = {**out, "fleet_nodes": summary}
    return out


def get_cluster(backend: Backend, cfg: Config, executor: Executor) -> dict[str, Any]:
    """reference: get/cluster.go:129-138 — plus a node-health table from
    the manager's kube API (preemption visibility, fleet/nodes.py), which
    the reference delegates to the Rancher UI."""
    manager = select_manager(backend, cfg)
    state = backend.state(manager)
    cluster_key = select_cluster(state, cfg)
    out = executor.output(state, cluster_key)

    from tpu_kubernetes.fleet import resolve_fleet_api
    from tpu_kubernetes.fleet.nodes import diagnose_nodes, expected_node_names

    fleet_api = resolve_fleet_api(executor, state, cluster_key)
    if fleet_api is not None:
        try:
            diagnosis = diagnose_nodes(
                fleet_api, expected_node_names(state, cluster_key)
            )
        except Exception as e:  # noqa: BLE001 — health is best-effort here
            out = {**out, "node_health_error": str(e)[:200]}
        else:
            out = {**out, "node_health": diagnosis}
    return out


def get_runs(backend: Backend, cfg: Config) -> list[dict[str, Any]]:
    """Run reports for the selected manager, oldest first (the backend
    orders by the ``runs/<millis>.json`` timestamp key). Each report is
    what util/runlog.py persisted: command, status, run_id, phase
    breakdown, and the terraform command metrics snapshot."""
    manager = select_manager(backend, cfg)
    return backend.run_reports(manager)


def format_runs(reports: list[dict[str, Any]], history: int = 10) -> str:
    """Human rendering: one summary line per run (newest first, capped at
    ``history``), then the newest run's phase breakdown — the answer to
    "what did the last create/destroy spend its time on"."""
    if not reports:
        return "no recorded runs\n"
    lines = []
    newest_first = list(reversed(reports))
    for r in newest_first[:history]:
        finished = r.get("finished_at")
        when = (
            time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(finished))
            if isinstance(finished, (int, float)) else "?"
        )
        lines.append(
            f"{when}  {r.get('command', '?'):<16} "
            f"{r.get('status', '?'):<6} {r.get('total_seconds', 0.0):>8.1f}s"
            f"  run_id={r.get('run_id', '-')}"
        )
    hidden = len(reports) - min(len(reports), history)
    if hidden:
        lines.append(f"… and {hidden} older run(s) — use --json for all")
    from tpu_kubernetes.util.runlog import runs_keep

    if len(reports) >= runs_keep():
        # the backends prune on write, newest kept — say so rather than
        # letting a full window read as "history begins here"
        lines.append(
            f"(retention cap reached: the backend keeps the newest "
            f"{runs_keep()} runs — TPU_K8S_RUNS_KEEP overrides)"
        )
    last = newest_first[0]
    lines.append("")
    lines.append(
        f"latest: {last.get('command', '?')} on "
        f"{last.get('manager', '?')!r} — {last.get('status', '?')}"
    )
    for p in last.get("phases", []):
        meta = {
            k: v for k, v in p.items() if k not in ("phase", "seconds")
        }
        suffix = f"  {meta}" if meta else ""
        lines.append(
            f"  {p.get('phase', '?'):<24} {p.get('seconds', 0.0):>8.3f}s{suffix}"
        )
    error = last.get("error")
    if error:
        lines.append(f"  error: {error}")
    return "\n".join(lines) + "\n"


def get_kubeconfig(backend: Backend, cfg: Config, executor: Executor) -> str:
    """Synthesize a working kubeconfig from the manager's live outputs —
    the aha-flow closer (see tpu_kubernetes/get/kubeconfig.py; reference
    analog: setup_rancher.sh.tpl:1-50 minting usable API credentials)."""
    from tpu_kubernetes.get.kubeconfig import (
        KubeconfigError,
        build_kubeconfig,
        fetch_ca_pem,
    )

    manager = select_manager(backend, cfg)
    state = backend.state(manager)
    outputs = executor.output(state, MANAGER_KEY)
    api_url = outputs.get("api_url")
    token = outputs.get("secret_key")
    if not api_url or not token:
        raise KubeconfigError(
            f"manager {manager!r} has no live api_url/secret_key outputs — "
            "has it been applied with terraform installed? (dry-run state "
            "documents carry no outputs)"
        )
    ca_pem = fetch_ca_pem(str(api_url))
    return build_kubeconfig(manager, str(api_url), str(token), ca_pem)
