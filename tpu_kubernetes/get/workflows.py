"""``get manager|cluster`` workflows: query live outputs.

reference: get/manager.go:16-96 and get/cluster.go:17-140 — render the state
to a temp dir, ``terraform init`` + ``terraform output`` for the module of
interest, print the result.
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.backend import Backend
from tpu_kubernetes.config import Config
from tpu_kubernetes.create.node import select_cluster, select_manager
from tpu_kubernetes.shell import Executor
from tpu_kubernetes.state import MANAGER_KEY


def get_manager(backend: Backend, cfg: Config, executor: Executor) -> dict[str, Any]:
    """reference: get/manager.go:83-92."""
    manager = select_manager(backend, cfg)
    state = backend.state(manager)
    return executor.output(state, MANAGER_KEY)


def get_cluster(backend: Backend, cfg: Config, executor: Executor) -> dict[str, Any]:
    """reference: get/cluster.go:129-138."""
    manager = select_manager(backend, cfg)
    state = backend.state(manager)
    cluster_key = select_cluster(state, cfg)
    return executor.output(state, cluster_key)
