"""Named Terraform-JSON state document.

The whole deployment (manager + clusters + nodes) for one cluster manager is a
single Terraform JSON document. Workflow code mutates it through this wrapper
and the executor applies it. Mirrors the reference's gabs-backed document
(reference: state/state.go:10-147) with the same key naming scheme:

  module."cluster-manager"                      — the manager module
  module."cluster_{provider}_{name}"            — one cluster
  module."node_{provider}_{cluster}_{hostname}" — one node

(reference: state/state.go:55-77). Cluster and node *names* are validated to
never contain ``_`` so prefix-scan parsing is unambiguous (the reference's
split-on-underscore parsing at state/state.go:149-160 silently breaks on such
names; we reject them at the door instead — see util/names.py), and never
contain ``.`` because module keys must be valid Terraform module names.
"""

from __future__ import annotations

import copy
import json
import re
from typing import Any, Iterator

MANAGER_KEY = "cluster-manager"

_NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9-]*$")


class StateError(Exception):
    pass


def _check_name(kind: str, name: str) -> None:
    if not _NAME_RE.match(name):
        raise StateError(
            f"invalid {kind} name {name!r}: must match [a-zA-Z0-9][a-zA-Z0-9-]* "
            "(underscores are key separators; dots are invalid in Terraform "
            "module names)"
        )


class State:
    """A named, mutable Terraform-JSON document.

    reference: state/state.go (New :20, Get :~30, SetManager :36,
    SetTerraformBackendConfig :45, AddCluster :55, AddNode :65, Delete :79,
    Bytes :88, Clusters :94, Nodes :117).
    """

    def __init__(self, name: str, data: bytes | str | dict[str, Any] | None = None):
        self.name = name
        if data is None or data == b"" or data == "":
            self._doc: dict[str, Any] = {}
        elif isinstance(data, dict):
            self._doc = copy.deepcopy(data)
        else:
            self._doc = json.loads(data)
            if not isinstance(self._doc, dict):
                raise StateError(f"state document for {name!r} is not a JSON object")
        self._scrub_retired_keys()

    # module-config keys that once existed but no module declares anymore;
    # documents persisted before their retirement must not fail terraform
    # validation forever (round 3 retired the dead rancher-image knobs —
    # k3s has no server/agent containers)
    _RETIRED_MODULE_KEYS = ("server_image", "agent_image")

    def _scrub_retired_keys(self) -> None:
        modules = self._doc.get("module")
        if not isinstance(modules, dict):
            return
        for config in modules.values():
            if isinstance(config, dict):
                for key in self._RETIRED_MODULE_KEYS:
                    config.pop(key, None)

    # -- dotted-path access ------------------------------------------------
    def get(self, path: str, default: Any = None) -> Any:
        node: Any = self._doc
        for part in path.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def set(self, path: str, value: Any) -> None:
        parts = path.split(".")
        node = self._doc
        for part in parts[:-1]:
            node = node.setdefault(part, {})
            if not isinstance(node, dict):
                raise StateError(f"path {path!r} collides with non-object value")
        node[parts[-1]] = value

    def delete(self, path: str) -> None:
        """Delete a path (no-op if absent). reference: state/state.go:79-86."""
        parts = path.split(".")
        node: Any = self._doc
        for part in parts[:-1]:
            if not isinstance(node, dict) or part not in node:
                return
            node = node[part]
        if isinstance(node, dict):
            node.pop(parts[-1], None)

    # -- module access -----------------------------------------------------
    # Module keys are plain dict keys, never dotted paths (robust regardless
    # of key content).
    def set_module(self, key: str, config: dict[str, Any]) -> None:
        self._doc.setdefault("module", {})[key] = config

    def module(self, key: str) -> dict[str, Any] | None:
        modules = self.get("module", {})
        return modules.get(key) if isinstance(modules, dict) else None

    def delete_module(self, key: str) -> None:
        modules = self.get("module")
        if isinstance(modules, dict):
            modules.pop(key, None)

    # -- manager / backend -------------------------------------------------
    def set_manager(self, config: dict[str, Any]) -> str:
        """Install the manager module config. reference: state/state.go:36-43."""
        key = MANAGER_KEY
        self.set_module(key, config)
        return key

    def manager(self) -> dict[str, Any] | None:
        return self.module(MANAGER_KEY)

    def set_terraform_backend_config(self, path: str, config: Any) -> None:
        """Inject the ``terraform.backend.*`` block so terraform's own tfstate
        is co-located with this document. reference: state/state.go:45-53,
        backend/backend.go:24-26."""
        self.set(path, config)

    # -- clusters ----------------------------------------------------------
    def add_cluster(self, provider: str, name: str, config: dict[str, Any]) -> str:
        """reference: state/state.go:55-62."""
        _check_name("provider", provider)
        _check_name("cluster", name)
        key = f"cluster_{provider}_{name}"
        self.set_module(key, config)
        return key

    def add_node(
        self, provider: str, cluster_name: str, hostname: str, config: dict[str, Any]
    ) -> str:
        """reference: state/state.go:65-77."""
        _check_name("provider", provider)
        _check_name("cluster", cluster_name)
        _check_name("hostname", hostname)
        key = f"node_{provider}_{cluster_name}_{hostname}"
        self.set_module(key, config)
        return key

    def _module_keys(self) -> Iterator[str]:
        modules = self.get("module", {})
        if isinstance(modules, dict):
            yield from modules.keys()

    def clusters(self) -> dict[str, str]:
        """Map of cluster name → module key, by prefix scan.
        reference: state/state.go:94-115."""
        out: dict[str, str] = {}
        for key in self._module_keys():
            parts = cluster_key_parts(key)
            if parts is not None:
                out[parts[1]] = key
        return out

    def nodes(self, cluster_key: str) -> dict[str, str]:
        """Map of hostname → module key for one cluster.
        reference: state/state.go:117-147."""
        parts = cluster_key_parts(cluster_key)
        if parts is None:
            raise StateError(f"not a cluster key: {cluster_key!r}")
        provider, cluster_name = parts
        prefix = f"node_{provider}_{cluster_name}_"
        out: dict[str, str] = {}
        for key in self._module_keys():
            if key.startswith(prefix):
                out[key[len(prefix):]] = key
        return out

    # -- serialization -----------------------------------------------------
    def to_bytes(self) -> bytes:
        """reference: state/state.go:88-92."""
        return json.dumps(self._doc, indent=2, sort_keys=True).encode()

    def to_dict(self) -> dict[str, Any]:
        return copy.deepcopy(self._doc)


def cluster_key_parts(key: str) -> tuple[str, str] | None:
    """Parse ``cluster_{provider}_{name}`` → (provider, name), else None.
    reference: state/state.go:149-160."""
    if not key.startswith("cluster_"):
        return None
    rest = key[len("cluster_"):]
    if "_" not in rest:
        return None
    provider, name = rest.split("_", 1)
    if not provider or not name or "_" in name:
        return None
    return provider, name


def node_key_parts(key: str) -> tuple[str, str, str] | None:
    """Parse ``node_{provider}_{cluster}_{hostname}`` → parts, else None."""
    if not key.startswith("node_"):
        return None
    rest = key[len("node_"):]
    pieces = rest.split("_")
    if len(pieces) != 3 or not all(pieces):
        return None
    return pieces[0], pieces[1], pieces[2]
