from tpu_kubernetes.state.document import (  # noqa: F401
    MANAGER_KEY,
    State,
    StateError,
    cluster_key_parts,
    node_key_parts,
)
