"""Runtime retrace sentinel: the dynamic half of the jaxcontract pass.

The static retrace checks in :mod:`jaxcontract` catch the *shapes* of
retrace bugs (a jitted closure over a per-call scalar, a bad
``static_argnums``); they cannot see a hash-unstable static argument
or a shape that drifts between steps. This module can, for any call
pattern a test actually drives: ``watching()`` monkeypatches
``jax.jit`` so that every function handed to it is wrapped with a
trace counter — the wrapper's Python body only executes while JAX is
tracing, so each execution *is* one compile of that program. Compiles
are keyed by (allocation site, function name, jit instance, input
signature), where the signature is the pytree structure plus per-leaf
shape/dtype (and ``repr`` for static leaves): the engine's deliberate
width buckets land on distinct signatures, and sibling engines built
in one test (the solo-vs-batched identity pattern) land on distinct
jit instances — neither reads as a retrace. What does is one compiled
program tracing twice for the same signature, the hash-unstable-static
/ dropped-cache bug the static pass cannot see.

Opt-in and zero-cost when off: the serve-identity suites run under it
when ``TPU_K8S_RETRACE=1`` (see tests/conftest.py and
``make jax-check``). ``check()`` raises :class:`RetraceError` if any
key compiled more than once — steady-state code must trace each
program exactly once — and ``report()`` includes per-key compile
counts plus total seconds spent tracing, the "where did startup time
go" number.

The monitor's own bookkeeping uses ``_thread.allocate_lock`` and an
injectable clock, mirroring :mod:`.lockgraph`.
"""

from __future__ import annotations

import _thread
import contextlib
import functools
import time
from typing import Callable

ENV_VAR = "TPU_K8S_RETRACE"


class RetraceError(RuntimeError):
    """A jitted program traced more than once for the same input
    signature — recompilation in what should be steady state."""


def _abstract(leaf) -> str:
    """One pytree leaf → a stable signature token: shape/dtype for
    arrays and tracers, ``repr`` for hashable statics."""
    aval = getattr(leaf, "aval", None)
    if aval is not None:
        shape = tuple(getattr(aval, "shape", ()))
        return f"{getattr(aval, 'dtype', '?')}{shape}"
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}{tuple(shape)}"
    return repr(leaf)


def _signature(args: tuple, kwargs: dict) -> tuple:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(_abstract(x) for x in leaves))


class RetraceMonitor:
    """Per-program compile counts + total trace-time accounting.

    Counts are kept per jit *instance* (one ``jax.jit(...)`` call) and
    aggregated per (site, name, signature) for reporting. The check is
    per instance: one compiled program tracing twice for the same input
    signature is the runtime retrace bug — a hash-unstable static, a
    dropped cache. Two engine instances each compiling ``prefill`` once
    at the same source line are *not* a retrace (the identity suites
    build a solo and a batched engine side by side on purpose); the
    per-call-rebuild shape (many instances from one site) is what the
    static ``retrace-captured-scalar`` rule exists for, and still shows
    up in the report's aggregated counts."""

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._mu = _thread.allocate_lock()
        self._clock = clock
        self._seq = 0
        # (site, fn name, jit instance, signature) -> compile count
        self._counts: dict[tuple, int] = {}
        self._trace_s = 0.0

    # -- instrumentation callback (called by the jit wrapper) ------------

    def note_trace(self, site: str, name: str, inst: int, sig: tuple,
                   seconds: float) -> None:
        key = (site, name, inst, sig)
        with self._mu:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._trace_s += seconds

    def wrap(self, fun, site: str):
        """Wrap ``fun`` so each execution of its body (i.e. each trace)
        is recorded under (site, name, jit instance, input signature)."""
        name = getattr(fun, "__name__", None)
        if name is None and isinstance(fun, functools.partial):
            name = getattr(fun.func, "__name__", None)
        name = name or type(fun).__name__
        monitor = self
        with self._mu:
            self._seq += 1
            inst = self._seq

        def traced(*args, **kwargs):
            t0 = monitor._clock()
            try:
                return fun(*args, **kwargs)
            finally:
                monitor.note_trace(site, name, inst,
                                   _signature(args, kwargs),
                                   monitor._clock() - t0)

        # functools.wraps by hand: partials lack __name__/__qualname__
        # and must not abort the copy; __wrapped__ keeps
        # inspect.signature (and jit's static_argnames lookup) honest
        for attr in ("__module__", "__name__", "__qualname__", "__doc__"):
            try:
                setattr(traced, attr, getattr(fun, attr))
            except AttributeError:
                pass
        traced.__dict__.update(getattr(fun, "__dict__", {}))
        traced.__wrapped__ = fun
        return traced

    # -- analysis --------------------------------------------------------

    @staticmethod
    def _render(site: str, name: str, sig: tuple) -> str:
        leaves = ", ".join(sig[1][:4])
        if len(sig[1]) > 4:
            leaves += f", +{len(sig[1]) - 4}"
        return f"{site} {name}({leaves})"

    def counts(self) -> dict[str, int]:
        """Rendered program key → total compile count across every jit
        instance at that (site, name, signature), deterministic order.
        A count above the number of instances means a real retrace; a
        count equal to it means that many programs were built there."""
        with self._mu:
            items = sorted(self._counts.items(),
                           key=lambda kv: (kv[0][0], kv[0][1],
                                           str(kv[0][3]), kv[0][2]))
        out: dict[str, int] = {}
        for (site, name, _inst, sig), n in items:
            key = self._render(site, name, sig)
            out[key] = out.get(key, 0) + n
        return out

    def retraced(self, max_compiles: int = 1) -> dict[str, int]:
        """Rendered key → worst per-instance compile count, for keys
        where a single jit instance traced more than ``max_compiles``
        times for one signature — the true runtime retraces."""
        with self._mu:
            items = sorted(self._counts.items(),
                           key=lambda kv: (kv[0][0], kv[0][1],
                                           str(kv[0][3]), kv[0][2]))
        out: dict[str, int] = {}
        for (site, name, _inst, sig), n in items:
            if n > max_compiles:
                key = self._render(site, name, sig)
                out[key] = max(out.get(key, 0), n)
        return out

    def total_trace_s(self) -> float:
        with self._mu:
            return self._trace_s

    def check(self, max_compiles: int = 1) -> None:
        bad = self.retraced(max_compiles)
        if bad:
            rendered = "; ".join(f"{k} compiled {n}x"
                                 for k, n in bad.items())
            raise RetraceError(
                f"program(s) retraced in steady state "
                f"(limit {max_compiles} compile(s) per signature): "
                f"{rendered}"
            )

    def report(self) -> dict:
        return {
            "programs": self.counts(),
            "total_trace_s": round(self.total_trace_s(), 6),
            "retraced": sorted(self.retraced()),
        }


def _alloc_site(skip_file: str) -> str:
    """Name a program by the source line that called ``jax.jit`` — the
    stable identity shared by every re-created engine that builds its
    programs there (mirrors lockgraph's lock naming)."""
    import sys

    frame = sys._getframe(2)
    while frame is not None and frame.f_code.co_filename == skip_file:
        frame = frame.f_back
    if frame is None:
        return "jit@?"
    fname = frame.f_code.co_filename.rsplit("/", 1)[-1]
    return f"{fname}:{frame.f_lineno}"


@contextlib.contextmanager
def watching(monitor: RetraceMonitor | None = None):
    """Instrument every ``jax.jit(...)`` call made inside the block;
    yields the monitor. Restores the real ``jax.jit`` on exit.
    Programs jitted before the block stay uninstrumented — build the
    engine inside the block for full coverage (the conftest fixture
    wraps each test)."""
    import jax

    m = monitor if monitor is not None else RetraceMonitor()
    orig_jit = jax.jit
    here = __file__

    def patched_jit(fun=None, *args, **kwargs):
        if fun is None:
            # decorator-with-options form: @jax.jit(static_argnums=...)
            def deco(f):
                return patched_jit(f, *args, **kwargs)
            return deco
        return orig_jit(m.wrap(fun, _alloc_site(here)), *args, **kwargs)

    jax.jit = patched_jit
    try:
        yield m
    finally:
        jax.jit = orig_jit
