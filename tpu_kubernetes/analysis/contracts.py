"""Closed-vocabulary contract lint: fault sites, metrics, ledger
classes, alert-rule kinds.

Pure AST + text scanning — the package is never imported. Each check
mirrors a vocabulary the runtime enforces loudly at one end only; this
pass closes the other end:

* ``FAULTS.fire("<site>")`` literals vs the ``SITES`` frozenset in
  obs/faults.py (`fault-site-unknown`, `fault-site-unfired`,
  `fault-site-dynamic`). configure() rejects an unknown site at arm
  time, but nothing notices a site that exists only in the set — a
  chaos matrix entry that can never fire.
* ``REGISTRY.counter/gauge/histogram`` registrations must use a literal
  ``tpu_[a-z0-9_]+`` name and literal label tuples
  (`metric-name-scheme`, `metric-labels-not-literal`); every metric the
  observability guide tables, examples/alerts.d rules, or monitor
  columns reference must resolve to a registration
  (`metric-unregistered`), and every registration must appear in the
  guide catalog (`metric-undocumented`).
* ``LEDGER.settle("<class>")`` literals vs obs/ledger.py ``CLASSES``
  (`ledger-class-unknown`).
* alerts.d ``"kind"`` values vs the ``@rule_kind`` registry
  (`alert-kind-unknown`). build_rule() rejects unknown kinds at load
  time; this catches them before a rule file ships.
* ``new_action("<kind>")`` literals vs the ``ACTION_KINDS`` frozenset
  in obs/controller.py (`action-kind-unknown`), and every registered
  kind must appear in the observability guide's action table
  (`action-kind-undocumented`) — a fleet remediation the docs don't
  name is an unauditable one.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path

from tpu_kubernetes.analysis import (
    METRIC_RE,
    METRIC_TOKEN_RE,
    Finding,
    Project,
    call_name,
    literal_str_seq,
    str_const,
)

METRIC_METHODS = ("counter", "gauge", "histogram")


def run(project: Project) -> list[Finding]:
    out: list[Finding] = []
    out.extend(_check_fault_sites(project))
    out.extend(_check_metrics(project))
    out.extend(_check_ledger_classes(project))
    out.extend(_check_alert_kinds(project))
    out.extend(_check_action_kinds(project))
    return out


# -- fault sites -----------------------------------------------------------

def _module_str_set(project: Project, var: str,
                    filename: str) -> tuple[Path | None, int, set[str]]:
    """Find the module-level ``var = frozenset({...})`` literal in the
    package file named ``filename``. Returns (path, line, values)."""
    for path in project.py_files():
        if path.name != filename:
            continue
        for node in project.parse(path).body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == var
                for t in node.targets
            ):
                vals = literal_str_seq(node.value)
                if vals is not None:
                    return path, node.lineno, set(vals)
    return None, 0, set()


def _fire_calls(project: Project):
    """Yield (path, call) for every ``<something>FAULTS.fire(...)`` /
    ``faults.fire(...)`` call in the package."""
    for path in project.py_files():
        for node in ast.walk(project.parse(path)):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name.endswith(".fire"):
                continue
            recv = name[: -len(".fire")]
            if "fault" in recv.lower():
                yield path, node


def _check_fault_sites(project: Project) -> list[Finding]:
    sites_path, sites_line, sites = _module_str_set(
        project, "SITES", "faults.py"
    )
    if sites_path is None:
        return []  # nothing to check against (not a faults-bearing tree)
    out: list[Finding] = []
    fired: set[str] = set()
    for path, call in _fire_calls(project):
        if not call.args:
            continue
        site = str_const(call.args[0])
        if site is None:
            out.append(Finding(
                "fault-site-dynamic", project.rel(path), call.lineno,
                call_name(call),
                "fire() with a non-literal site — the closed SITES "
                "vocabulary cannot be checked through a variable",
            ))
            continue
        fired.add(site)
        if site not in sites:
            out.append(Finding(
                "fault-site-unknown", project.rel(path), call.lineno,
                site,
                f"fire({site!r}) is not in the SITES vocabulary "
                f"({project.rel(sites_path)})",
            ))
    for site in sorted(sites - fired):
        out.append(Finding(
            "fault-site-unfired", project.rel(sites_path), sites_line,
            site,
            f"SITES entry {site!r} has no fire() call site — a chaos "
            "site that can never fire tests nothing",
        ))
    return out


# -- metrics ---------------------------------------------------------------

def _registrations(project: Project):
    """Yield (path, call, name_or_None) for every
    ``<registry>.counter/gauge/histogram(...)`` call. ``name`` resolves
    literals and the ``metric``-parameter-default idiom (PhaseProfiler
    takes ``metric: str = "tpu_..."`` and registers through the
    variable); None means genuinely dynamic."""
    for path in project.py_files():
        tree = project.parse(path)
        # parameter defaults named like the first arg they flow into
        param_defaults: dict[str, str] = {}
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = fn.args
                pos = args.posonlyargs + args.args
                defaults = args.defaults
                for a, d in zip(pos[len(pos) - len(defaults):], defaults):
                    s = str_const(d)
                    if s is not None:
                        param_defaults[a.arg] = s
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    s = str_const(d) if d is not None else None
                    if s is not None:
                        param_defaults[a.arg] = s
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in METRIC_METHODS):
                continue
            name_node = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    name_node = kw.value
            if name_node is None:
                continue
            name = str_const(name_node)
            if name is None and isinstance(name_node, ast.Name):
                name = param_defaults.get(name_node.id)
            yield path, node, name


def _referenced_metrics(project: Project) -> dict[str, tuple[str, int]]:
    """Metric names the outside surfaces point at → (where, line).
    Sources: the observability guide tables, alerts.d rule files, and
    module-level column constants in monitor.py."""
    refs: dict[str, tuple[str, int]] = {}
    if project.metric_doc is not None:
        rel = project.rel(project.metric_doc)
        text = project.metric_doc.read_text(encoding="utf-8")
        for i, line in enumerate(text.splitlines(), 1):
            for tok in METRIC_TOKEN_RE.findall(line):
                refs.setdefault(tok, (rel, i))
    for path in project.alert_files:
        rel = project.rel(path)
        data = json.loads(path.read_text(encoding="utf-8"))
        for tok in _json_strings(data):
            if METRIC_RE.match(tok):
                refs.setdefault(tok, (rel, 1))
    for path in project.py_files():
        if path.name != "monitor.py":
            continue
        rel = project.rel(path)
        for node in project.parse(path).body:
            if isinstance(node, ast.Assign):
                s = str_const(node.value)
                if s is not None and METRIC_RE.match(s):
                    refs.setdefault(s, (rel, node.lineno))
    return refs


def _json_strings(data):
    if isinstance(data, str):
        yield data
    elif isinstance(data, dict):
        for v in data.values():
            yield from _json_strings(v)
    elif isinstance(data, list):
        for v in data:
            yield from _json_strings(v)


def _indirect_registrations(project: Project) -> dict[str, tuple[str, int]]:
    """Literal ``metric="tpu_..."`` keyword arguments at arbitrary call
    sites — the PhaseProfiler idiom, where the constructor registers the
    family through its parameter. These count as registered (and as
    needing documentation) but aren't registration calls themselves."""
    out: dict[str, tuple[str, int]] = {}
    for path in project.py_files():
        rel = project.rel(path)
        for node in ast.walk(project.parse(path)):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in METRIC_METHODS:
                continue  # direct registrations handled elsewhere
            for kw in node.keywords:
                if kw.arg == "metric":
                    s = str_const(kw.value)
                    if s is not None and METRIC_RE.match(s):
                        out.setdefault(s, (rel, node.lineno))
    return out


def _check_metrics(project: Project) -> list[Finding]:
    out: list[Finding] = []
    registered: set[str] = set()
    reg_sites: dict[str, tuple[str, int]] = {}
    for name, site in _indirect_registrations(project).items():
        registered.add(name)
        reg_sites.setdefault(name, site)
    for path, call, name in _registrations(project):
        rel = project.rel(path)
        if name is None:
            out.append(Finding(
                "metric-name-scheme", rel, call.lineno, call_name(call),
                "metric registered through a dynamic name — the catalog "
                "cross-check needs a literal (or a literal parameter "
                "default)",
            ))
        else:
            registered.add(name)
            reg_sites.setdefault(name, (rel, call.lineno))
            if not METRIC_RE.match(name):
                out.append(Finding(
                    "metric-name-scheme", rel, call.lineno, name,
                    f"metric name {name!r} does not match the "
                    "tpu_[a-z0-9_]+ scheme",
                ))
        for kw in call.keywords:
            if kw.arg == "labelnames" \
                    and literal_str_seq(kw.value) is None:
                out.append(Finding(
                    "metric-labels-not-literal", rel, call.lineno,
                    name or call_name(call),
                    "labelnames= must be a literal tuple of string "
                    "literals (label cardinality is part of the metric "
                    "contract)",
                ))
    refs = _referenced_metrics(project)
    refs.pop(project.pkg.name, None)  # 'tpu_kubernetes' in doc paths
    for name in sorted(set(refs) - registered):
        where, line = refs[name]
        out.append(Finding(
            "metric-unregistered", where, line, name,
            f"{name!r} is referenced here but no "
            "REGISTRY.counter/gauge/histogram registers it",
        ))
    if project.metric_doc is not None:
        doc_tokens = set(METRIC_TOKEN_RE.findall(
            project.metric_doc.read_text(encoding="utf-8")
        ))
        # scheme violations already got their own finding — don't also
        # demand documentation for a name that must be renamed anyway
        for name in sorted(n for n in registered - doc_tokens
                           if METRIC_RE.match(n)):
            where, line = reg_sites[name]
            out.append(Finding(
                "metric-undocumented", where, line, name,
                f"{name!r} is registered but missing from the "
                f"{project.rel(project.metric_doc)} catalog",
            ))
    return out


# -- ledger classes --------------------------------------------------------

def _ledger_classes(project: Project) -> set[str]:
    """The CLASSES tuple in ledger.py — elements are module-level
    constants (USEFUL = "useful"; CLASSES = (USEFUL, ...)), so resolve
    Name elements through the module's constant assignments."""
    for path in project.py_files():
        if path.name != "ledger.py":
            continue
        tree = project.parse(path)
        consts: dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                s = str_const(node.value)
                if s is not None:
                    consts[node.targets[0].id] = s
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "CLASSES"
                for t in node.targets
            ) and isinstance(node.value, (ast.Tuple, ast.List)):
                vals = set()
                for el in node.value.elts:
                    s = str_const(el)
                    if s is None and isinstance(el, ast.Name):
                        s = consts.get(el.id)
                    if s is not None:
                        vals.add(s)
                return vals
    return set()


def _check_ledger_classes(project: Project) -> list[Finding]:
    classes = _ledger_classes(project)
    if not classes:
        return []
    out: list[Finding] = []
    for path in project.py_files():
        for node in ast.walk(project.parse(path)):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not (name.endswith(".settle")
                    or name.endswith(".settle_request")):
                continue
            if not node.args:
                continue
            cls = str_const(node.args[0])
            if cls is not None and cls not in classes:
                out.append(Finding(
                    "ledger-class-unknown", project.rel(path),
                    node.lineno, cls,
                    f"settle class {cls!r} is not in the ledger CLASSES "
                    f"vocabulary ({sorted(classes)})",
                ))
    return out


# -- alert-rule kinds ------------------------------------------------------

def _check_alert_kinds(project: Project) -> list[Finding]:
    kinds: set[str] = set()
    for path in project.py_files():
        for node in ast.walk(project.parse(path)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for deco in node.decorator_list:
                    if isinstance(deco, ast.Call) \
                            and call_name(deco).endswith("rule_kind") \
                            and deco.args:
                        s = str_const(deco.args[0])
                        if s is not None:
                            kinds.add(s)
    if not kinds:
        return []
    out: list[Finding] = []
    for path in project.alert_files:
        data = json.loads(path.read_text(encoding="utf-8"))
        rules = data.get("rules", data) if isinstance(data, dict) else data
        if not isinstance(rules, list):
            continue
        for rule in rules:
            if not isinstance(rule, dict):
                continue
            kind = rule.get("kind")
            if isinstance(kind, str) and kind not in kinds:
                out.append(Finding(
                    "alert-kind-unknown", project.rel(path), 1, kind,
                    f"rule kind {kind!r} is not registered via "
                    "@rule_kind (build_rule would reject this file at "
                    "load time)",
                ))
    return out


# -- controller action kinds -----------------------------------------------

def _check_action_kinds(project: Project) -> list[Finding]:
    """The fleet controller's closed remediation vocabulary, checked
    both ways like fault sites: every ``new_action("<kind>")`` literal
    must be in the ``ACTION_KINDS`` frozenset (runtime new_action()
    raises, but only when the branch runs), and every registered kind
    must be named in the observability guide — the action table IS the
    operator's contract for what a self-driving fleet may do."""
    kinds_path, kinds_line, kinds = _module_str_set(
        project, "ACTION_KINDS", "controller.py"
    )
    if kinds_path is None:
        return []  # not a controller-bearing tree
    out: list[Finding] = []
    for path in project.py_files():
        for node in ast.walk(project.parse(path)):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not (name == "new_action" or name.endswith(".new_action")):
                continue
            if not node.args:
                continue
            kind = str_const(node.args[0])
            if kind is not None and kind not in kinds:
                out.append(Finding(
                    "action-kind-unknown", project.rel(path),
                    node.lineno, kind,
                    f"new_action({kind!r}) is not in the ACTION_KINDS "
                    f"vocabulary ({project.rel(kinds_path)})",
                ))
    if project.metric_doc is not None:
        doc_text = project.metric_doc.read_text(encoding="utf-8")
        for kind in sorted(kinds):
            if not re.search(rf"\b{re.escape(kind)}\b", doc_text):
                out.append(Finding(
                    "action-kind-undocumented", project.rel(kinds_path),
                    kinds_line, kind,
                    f"action kind {kind!r} is registered but missing "
                    f"from the {project.rel(project.metric_doc)} "
                    "action table",
                ))
    return out
