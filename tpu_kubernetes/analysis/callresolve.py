"""Interprocedural call resolution for the AST passes.

The purity fixpoint in :mod:`jaxcontract` needs to follow a call from
``serve/server.py`` into ``models/decode.py`` and onward into
``ops/flash_attention.py`` without importing any of them; the
concurrency pass needs the narrower ``self.helper()`` resolution for
its lock-context fixpoint. Both shapes live here so they stay
consistent: one index of every function/method definition in the
package plus every import alias, and one resolver that turns a dotted
call name (as :func:`tpu_kubernetes.analysis.call_name` renders it)
back into the definition it lands on.

Resolution is deliberately best-effort and *under*-approximate: a call
that cannot be resolved (a bound method on an arbitrary object, a
function received as a parameter, anything outside the package) is
skipped, never guessed. The passes that build on this are linting for
hazards, where a false positive costs more than a miss.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from tpu_kubernetes.analysis import Project


@dataclass(frozen=True)
class FuncRef:
    """One function/method definition: where it is and its AST."""

    module: str                 # repo-relative path, forward slashes
    qualname: str               # "fn" or "Class.fn"
    node: ast.AST               # FunctionDef / AsyncFunctionDef / Lambda
    path: Path


@dataclass
class ModuleInfo:
    path: Path
    rel: str
    # local name -> dotted module target ("jnp" -> "jax.numpy");
    # includes function-level imports (they resolve the same way)
    import_alias: dict[str, str] = field(default_factory=dict)
    # local name -> (source module dotted, original name) for
    # ``from X import Y [as Z]``
    from_imports: dict[str, tuple[str, str]] = field(default_factory=dict)
    # top-level defs and methods by qualname
    defs: dict[str, FuncRef] = field(default_factory=dict)


def self_method_call(name: str) -> str | None:
    """``self.helper`` → ``helper`` for intra-class call-site
    resolution (the concurrency pass's lock-context fixpoint); any
    other shape resolves to None."""
    parts = name.split(".")
    if len(parts) == 2 and parts[0] == "self":
        return parts[1]
    return None


class CallIndex:
    """Package-wide function index + import-aware call resolver."""

    def __init__(self, project: Project):
        self.project = project
        self.pkg_name = project.pkg.name
        self.modules: dict[str, ModuleInfo] = {}
        for path in project.py_files():
            rel = project.rel(path)
            info = ModuleInfo(path=path, rel=rel)
            tree = project.parse(path)
            self._index_imports(tree, info)
            self._index_defs(tree, info)
            self.modules[self._dotted(path)] = info

    # -- construction -----------------------------------------------------

    def _dotted(self, path: Path) -> str:
        """File path → dotted module name rooted at the package."""
        rel = path.resolve().relative_to(self.project.pkg.parent)
        parts = list(rel.with_suffix("").parts)
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _index_imports(self, tree: ast.Module, info: ModuleInfo) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    info.import_alias[alias.asname or
                                      alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    info.from_imports[alias.asname or alias.name] = \
                        (node.module, alias.name)

    def _index_defs(self, tree: ast.Module, info: ModuleInfo) -> None:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.defs[node.name] = FuncRef(
                    info.rel, node.name, node, info.path)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        q = f"{node.name}.{sub.name}"
                        info.defs[q] = FuncRef(info.rel, q, sub, info.path)
                        # methods also resolve bare for self.X() chains
                        info.defs.setdefault(
                            sub.name,
                            FuncRef(info.rel, q, sub, info.path))

    # -- resolution -------------------------------------------------------

    def module_of(self, path: Path) -> ModuleInfo | None:
        return self.modules.get(self._dotted(path))

    def resolve(self, name: str, mod: ModuleInfo,
                cls: str | None = None) -> FuncRef | None:
        """Resolve a dotted call name seen inside ``mod`` (optionally
        inside class ``cls``) to the FuncRef it lands on, or None."""
        parts = name.split(".")
        if len(parts) == 1:
            # local def, or ``from X import Y``
            if cls is not None and f"{cls}.{parts[0]}" in mod.defs:
                return mod.defs[f"{cls}.{parts[0]}"]
            if parts[0] in mod.defs:
                ref = mod.defs[parts[0]]
                # bare method names only resolve inside their class
                if "." in ref.qualname and cls is None:
                    return None
                return ref
            src = mod.from_imports.get(parts[0])
            if src is not None:
                return self._lookup(src[0], src[1])
            return None
        if parts[0] == "self" and len(parts) == 2 and cls is not None:
            return mod.defs.get(f"{cls}.{parts[1]}")
        # ``alias.attr...`` through ``import X [as alias]``
        target = mod.import_alias.get(parts[0])
        if target is not None:
            return self._lookup(".".join([target] + parts[1:-1]),
                                parts[-1])
        # ``from X import Y`` where Y is a module
        src = mod.from_imports.get(parts[0])
        if src is not None and len(parts) == 2:
            return self._lookup(f"{src[0]}.{src[1]}", parts[1])
        return None

    def _lookup(self, module: str, func: str,
                _depth: int = 0) -> FuncRef | None:
        info = self.modules.get(module)
        if info is None:
            return None
        ref = info.defs.get(func)
        if ref is not None:
            return ref
        # re-export chain: ``from tpu_kubernetes.ops import
        # flash_attention`` lands on ops/__init__.py, which itself
        # does ``from .flash_attention import flash_attention``
        if _depth < 8:
            src = info.from_imports.get(func)
            if src is not None:
                return self._lookup(src[0], src[1], _depth + 1)
            # or the name is a submodule: X.Y where Y is a module
            sub = self.modules.get(f"{module}.{func}")
            if sub is not None:
                return None
        return None
