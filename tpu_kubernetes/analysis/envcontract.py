"""Env contract lint: every ``TPU_K8S_*`` / ``SERVE_*`` / ``SERVER_*``
read documented, every documented knob actually read.

The env surface is the operational API of the stack — the serve job's
module docstring is the canonical cross-ref and the guide tables are
what an operator greps. Both drift: a knob added under deadline never
gets a table row (`env-undocumented`), and a renamed knob leaves a dead
row behind (`env-stale-doc`).

Detection is literal-based: any string constant in package code that
full-matches one of the prefixes counts as a read site — this catches
direct ``os.environ.get`` calls, ``env.get`` through an injected
mapping, the ``ENV_VAR = "..."`` module-constant idiom, and the
util/envparse helpers uniformly. Documentation sources are the
markdown guides, README, and module-level docstrings; a doc token with
a trailing underscore (a family wildcard like a ``*``-suffixed
mention) is ignored — the family's members are documented
individually. Staleness additionally accepts reads from the test tree:
suite-only switches are documented on purpose.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tpu_kubernetes.analysis import (
    ENV_PREFIX_RE,
    ENV_TOKEN_RE,
    Finding,
    Project,
    str_const,
)


def run(project: Project) -> list[Finding]:
    reads = _code_reads(project, project.py_files())
    test_reads: dict[str, tuple[str, int]] = {}
    if project.tests_dir is not None:
        # tests_py_files excludes tests/fixtures/ — the intentional
        # violation packages must not register as real read sites
        test_reads = _code_reads(project, project.tests_py_files(),
                                 lenient=True)
    documented = _documented(project)

    out: list[Finding] = []
    for var in sorted(set(reads) - set(documented)):
        rel, line = reads[var]
        out.append(Finding(
            "env-undocumented", rel, line, var,
            f"{var} is read here but has no row in the guide tables or "
            "the serve job docstring cross-ref",
        ))
    for var in sorted(set(documented) - set(reads) - set(test_reads)):
        rel, line = documented[var]
        out.append(Finding(
            "env-stale-doc", rel, line, var,
            f"{var} is documented here but nothing in the package or "
            "tests reads it",
        ))
    return out


def _code_reads(project: Project, files: list[Path], *,
                lenient: bool = False) -> dict[str, tuple[str, int]]:
    """var → first (path, line) where a string constant full-matches an
    env prefix. Docstrings can't collide: they never *equal* a bare var
    name, and substring mentions don't count as reads."""
    reads: dict[str, tuple[str, int]] = {}
    for path in files:
        try:
            tree = project.parse(path)
        except SyntaxError:
            if lenient:
                continue
            raise
        rel = project.rel(path)
        for node in ast.walk(tree):
            s = str_const(node) if isinstance(node, ast.Constant) else None
            if s is not None and ENV_PREFIX_RE.match(s):
                reads.setdefault(s, (rel, node.lineno))
    return reads


def _documented(project: Project) -> dict[str, tuple[str, int]]:
    """var → first (path, line) across the markdown guides and package
    module docstrings (the serve/job.py cross-ref among them)."""
    docs: dict[str, tuple[str, int]] = {}
    for path in project.doc_files:
        rel = project.rel(path)
        for i, line in enumerate(
                path.read_text(encoding="utf-8").splitlines(), 1):
            for tok in ENV_TOKEN_RE.findall(line):
                if not tok.endswith("_"):
                    docs.setdefault(tok, (rel, i))
    for path in project.py_files():
        tree = project.parse(path)
        doc = ast.get_docstring(tree, clean=False)
        if doc:
            rel = project.rel(path)
            for tok in ENV_TOKEN_RE.findall(doc):
                if not tok.endswith("_"):
                    docs.setdefault(tok, (rel, 1))
    return docs
