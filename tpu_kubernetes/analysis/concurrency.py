"""Concurrency-discipline lint: unguarded shared-state writes and
blocking calls under a lock.

Scope is self-selecting: any class that owns a ``threading.Lock`` /
``RLock`` / ``Condition`` attribute (``self._lock = threading.Lock()``,
``self._cv = threading.Condition()``) is treated as shared-state, and
the attributes it ever writes *under* that lock become the guarded
set. A ``with self._cv`` block acquires the Condition's underlying
lock, so it counts as a lock context like any other. Two findings:

* `lock-unguarded-write` — a guarded attribute written outside a
  ``with self.<lock>`` region. Exemptions keep the pass honest about
  the codebase's real discipline:

  - ``__init__`` (construction happens-before publication);
  - *lock-context methods*: a method whose every intra-class call site
    is under the lock, inside ``__init__``, or inside another
    lock-context method (fixpoint). This is the
    ``TokenLedger._zero`` shape — called unlocked from ``__init__``
    and under the lock from ``reset()`` — which is correct and must
    not be flagged;
  - an explicit ``# lint: unlocked-ok`` pragma on the write line, for
    documented single-owner state (the escape hatch is visible in the
    diff, unlike a baseline entry).

* `lock-blocking-call` — ``time.sleep`` / ``urlopen`` /
  ``subprocess.*`` (the terraform exec path) lexically inside a
  ``with self.<lock>`` block: the scheduler-stall bug class, where one
  slow I/O under the engine lock freezes every request thread.

Nested functions (thread bodies, callbacks) reset the lock context —
a ``def`` under a ``with`` runs later, not under the lock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tpu_kubernetes.analysis import Finding, Project, call_name
from tpu_kubernetes.analysis.callresolve import self_method_call

# Condition wraps a lock and `with self._cv` acquires it — attributes
# written under a Condition context are lock-guarded exactly like
# attributes written under the bare lock it wraps
LOCK_FACTORIES = ("Lock", "RLock", "Condition", "InstrumentedLock")
PRAGMA = "lint: unlocked-ok"


def run(project: Project) -> list[Finding]:
    out: list[Finding] = []
    for path in project.py_files():
        tree = project.parse(path)
        lines = path.read_text(encoding="utf-8").splitlines()
        rel = project.rel(path)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                out.extend(_check_class(node, rel, lines))
    return out


@dataclass
class _Write:
    attr: str
    line: int
    under: bool
    method: str


@dataclass
class _Call:
    name: str
    line: int
    under: bool
    method: str


@dataclass
class _Scan:
    writes: list[_Write] = field(default_factory=list)
    calls: list[_Call] = field(default_factory=list)


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    """Attributes holding lock objects: ``self.X = threading.Lock()``
    in any method, or a class-level ``X = Lock()``."""
    attrs: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and call_name(node.value).split(".")[-1] in LOCK_FACTORIES):
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                attrs.add(t.attr)
            elif isinstance(t, ast.Name) and node in cls.body:
                attrs.add(t.id)
    return attrs


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _write_targets(node: ast.AST):
    """Yield (attr, line) for self-attribute write targets, including
    ``self.x[...] = ...`` item writes (the dict/deque counters are the
    shared state that matters most)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _write_targets(el)
        return
    attr = _self_attr(node)
    if attr is not None:
        yield attr, node.lineno
        return
    if isinstance(node, ast.Subscript):
        attr = _self_attr(node.value)
        if attr is not None:
            yield attr, node.lineno


def _is_lock_ctx(item: ast.withitem, locks: set[str]) -> bool:
    expr = item.context_expr
    attr = _self_attr(expr)
    if attr is None and isinstance(expr, ast.Name):
        attr = expr.id
    return attr in locks


def _scan_method(method: ast.FunctionDef, locks: set[str]) -> _Scan:
    scan = _Scan()

    def visit(node: ast.AST, under: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # deferred execution: a nested def is NOT under the lock
            for child in ast.iter_child_nodes(node):
                visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = under or any(
                _is_lock_ctx(i, locks) for i in node.items
            )
            for i in node.items:
                visit(i, under)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for attr, line in _write_targets(t):
                    scan.writes.append(
                        _Write(attr, line, under, method.name)
                    )
        if isinstance(node, ast.Delete):
            for t in node.targets:
                for attr, line in _write_targets(t):
                    scan.writes.append(
                        _Write(attr, line, under, method.name)
                    )
        if isinstance(node, ast.Call):
            scan.calls.append(
                _Call(call_name(node), node.lineno, under, method.name)
            )
        for child in ast.iter_child_nodes(node):
            visit(child, under)

    for child in method.body:
        visit(child, False)
    return scan


def _blocking(name: str) -> bool:
    return (
        name == "time.sleep"
        or name.endswith(".urlopen") or name == "urlopen"
        or name.startswith("subprocess.")
    )


def _check_class(cls: ast.ClassDef, rel: str,
                 lines: list[str]) -> list[Finding]:
    locks = _lock_attrs(cls)
    if not locks:
        return []
    methods = [
        n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    scans = {m.name: _scan_method(m, locks) for m in methods}

    guarded: set[str] = set()
    for scan in scans.values():
        for w in scan.writes:
            if w.under and w.attr not in locks:
                guarded.add(w.attr)

    # lock-context fixpoint: a method all of whose intra-class call
    # sites are under the lock / in __init__ / in a lock-context method
    sites: dict[str, list[_Call]] = {m.name: [] for m in methods}
    for scan in scans.values():
        for c in scan.calls:
            method = self_method_call(c.name)
            if method is not None and method in sites:
                sites[method].append(c)
    lock_ctx: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, callers in sites.items():
            if name in lock_ctx or name == "__init__" or not callers:
                continue
            if all(
                c.under or c.method == "__init__" or c.method in lock_ctx
                for c in callers
            ):
                lock_ctx.add(name)
                changed = True

    out: list[Finding] = []
    for scan in scans.values():
        for w in scan.writes:
            if w.under or w.attr not in guarded:
                continue
            if w.method == "__init__" or w.method in lock_ctx:
                continue
            src = lines[w.line - 1] if w.line <= len(lines) else ""
            if PRAGMA in src:
                continue
            out.append(Finding(
                "lock-unguarded-write", rel, w.line,
                f"{cls.name}.{w.attr}",
                f"{cls.name}.{w.attr} is written under "
                f"self.{sorted(locks)[0]} elsewhere but not here "
                f"(method {w.method})",
            ))
        for c in scan.calls:
            if c.under and _blocking(c.name):
                out.append(Finding(
                    "lock-blocking-call", rel, c.line,
                    f"{cls.name}.{c.method}",
                    f"blocking call {c.name}() while holding a lock in "
                    f"{cls.name}.{c.method} — every other thread on "
                    "this lock stalls behind the I/O",
                ))
    return out
