"""Runtime lock-order watchdog: the dynamic half of the analyzer.

The static concurrency pass proves writes happen under *a* lock; it
cannot prove two locks are always taken in the same order. This module
can, for any interleaving a test actually drives: an instrumented
``threading.Lock`` records, per thread, the set of locks held at every
acquire and folds them into a process-wide directed graph — an edge
``A → B`` means "some thread held A while acquiring B". A cycle in that
graph is a potential deadlock *even if the run never deadlocked*: two
threads that took ``A→B`` and ``B→A`` on different runs only need the
right preemption point to stick forever.

Opt-in and zero-cost when off: ``watching()`` monkeypatches
``threading.Lock``/``RLock`` for the duration (so every lock the
serve engine / pools / aggregator allocate inside the block is
instrumented), and the resilience/chaos suites run under it when
``TPU_K8S_LOCKGRAPH=1`` (see tests/conftest.py and
``make resilience-check``). ``check()`` raises :class:`LockOrderError`
on a cycle; ``report()`` includes per-lock max hold times — the
"what's the longest anyone sat on the engine lock" number the
scheduler-stall postmortems always want.

The graph's own bookkeeping uses ``_thread.allocate_lock`` — the raw
primitive — so instrumentation can never recurse into itself, and the
clock is injectable so hold-time tests don't sleep.
"""

from __future__ import annotations

import _thread
import contextlib
import threading
import time
from typing import Callable

ENV_VAR = "TPU_K8S_LOCKGRAPH"


class LockOrderError(RuntimeError):
    """A cycle in the observed lock-acquisition graph — a potential
    deadlock, reported even though this run happened not to hang."""


class LockGraph:
    """Cross-thread lock-acquisition graph + hold-time accounting."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._mu = _thread.allocate_lock()
        self._clock = clock
        # thread ident -> stack of (lock, t_acquired)
        self._held: dict[int, list[tuple["InstrumentedLock", float]]] = {}
        # (holder name, acquired name) -> count
        self._edges: dict[tuple[str, str], int] = {}
        self._acquires: dict[str, int] = {}
        self._max_hold: dict[str, float] = {}

    # -- instrumentation callbacks (called by InstrumentedLock) ----------

    def note_acquired(self, lock: "InstrumentedLock") -> None:
        ident = _thread.get_ident()
        now = self._clock()
        with self._mu:
            stack = self._held.setdefault(ident, [])
            self._acquires[lock.name] = self._acquires.get(lock.name, 0) + 1
            for held, _t0 in stack:
                if held is lock:      # reentrant re-acquire: no edge
                    break
            else:
                for held, _t0 in stack:
                    edge = (held.name, lock.name)
                    self._edges[edge] = self._edges.get(edge, 0) + 1
            stack.append((lock, now))

    def note_released(self, lock: "InstrumentedLock") -> None:
        ident = _thread.get_ident()
        now = self._clock()
        with self._mu:
            stack = self._held.get(ident, [])
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] is lock:
                    _l, t0 = stack.pop(i)
                    dt = now - t0
                    if dt > self._max_hold.get(lock.name, -1.0):
                        self._max_hold[lock.name] = dt
                    break

    # -- analysis --------------------------------------------------------

    def edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def cycles(self) -> list[list[str]]:
        """Every elementary cycle reachable by DFS, as name lists
        (first == last). Deterministic: adjacency is sorted."""
        adj: dict[str, list[str]] = {}
        for a, b in sorted(self.edges()):
            adj.setdefault(a, []).append(b)
        cycles: list[list[str]] = []
        seen_keys: set[tuple[str, ...]] = set()

        def dfs(node: str, path: list[str], on_path: set[str],
                done: set[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = tuple(sorted(cyc[:-1]))
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(cyc)
                elif nxt not in done:
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path, done)
                    on_path.discard(nxt)
            done.add(node)

        done: set[str] = set()
        for start in sorted(adj):
            if start not in done:
                dfs(start, [start], {start}, done)
        return cycles

    def check(self) -> None:
        cycles = self.cycles()
        if cycles:
            rendered = "; ".join(" -> ".join(c) for c in cycles)
            raise LockOrderError(
                f"lock-order cycle(s) observed (potential deadlock): "
                f"{rendered}"
            )

    def report(self) -> dict:
        with self._mu:
            locks = {
                name: {
                    "acquires": self._acquires.get(name, 0),
                    "max_hold_s": round(self._max_hold.get(name, 0.0), 6),
                }
                for name in sorted(self._acquires)
            }
            edges = [
                {"from": a, "to": b, "count": n}
                for (a, b), n in sorted(self._edges.items())
            ]
        return {"locks": locks, "edges": edges, "cycles": self.cycles()}


class InstrumentedLock:
    """API-complete stand-in for ``threading.Lock``/``RLock`` that
    reports acquisitions to a :class:`LockGraph`. Reentrant when
    wrapping an RLock (re-acquire by the holder adds no edge)."""

    def __init__(self, graph: LockGraph, inner=None,
                 name: str | None = None):
        self._graph = graph
        self._inner = inner if inner is not None \
            else _thread.allocate_lock()
        self.name = name or f"lock@{id(self):#x}"

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if timeout == -1:
            ok = self._inner.acquire(blocking)
        else:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph.note_acquired(self)
        return ok

    def release(self) -> None:
        self._graph.note_released(self)
        self._inner.release()

    # -- threading.Condition protocol ------------------------------------
    # Condition lifts these off its lock in __init__; without them it
    # falls back to a try-acquire ownership probe that is wrong for a
    # reentrant inner lock (an owner's acquire(False) *succeeds*, so
    # notify()/wait() raise "un-acquired lock" for the actual owner).

    def _is_owned(self) -> bool:
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        # Condition.wait: fully drop the lock (all reentrant counts)
        self._graph.note_released(self)
        saver = getattr(self._inner, "_release_save", None)
        if saver is not None:
            return saver()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        self._graph.note_acquired(self)

    def _at_fork_reinit(self) -> None:
        # stdlib modules (concurrent.futures.thread) register this with
        # os.register_at_fork on a module-level threading.Lock()
        inner_reinit = getattr(self._inner, "_at_fork_reinit", None)
        if inner_reinit is not None:
            inner_reinit()
        else:
            self._inner = _thread.allocate_lock()

    def locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        if inner.acquire(False):      # RLock without locked()
            inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self.name}>"


def _alloc_site(skip_file: str) -> str:
    """Name a lock by the source line that allocated it — the stable,
    human-meaningful identity (``server.py:1507``), shared by every
    instance a re-created engine allocates there."""
    import sys

    # skip our own frames AND stdlib threading.py: a lock allocated by
    # Condition()'s default RLock() must take the *caller's* identity,
    # or every Condition in the process would merge into one graph node
    # (shared names merge edges, which can manufacture false cycles)
    skip = (skip_file, threading.__file__)
    frame = sys._getframe(2)
    while frame is not None and frame.f_code.co_filename in skip:
        frame = frame.f_back
    if frame is None:
        return "lock@?"
    fname = frame.f_code.co_filename.rsplit("/", 1)[-1]
    return f"{fname}:{frame.f_lineno}"


@contextlib.contextmanager
def watching(graph: LockGraph | None = None):
    """Instrument every ``threading.Lock()`` / ``threading.RLock()``
    allocated inside the block; yields the graph. Restores the real
    factories on exit. Locks allocated before the block stay
    uninstrumented — run setup inside the block for full coverage
    (the conftest fixture patches for the whole session)."""
    g = graph if graph is not None else LockGraph()
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    here = __file__

    def make_lock():
        return InstrumentedLock(g, orig_lock(), name=_alloc_site(here))

    def make_rlock():
        return InstrumentedLock(g, orig_rlock(), name=_alloc_site(here))

    threading.Lock = make_lock      # type: ignore[assignment]
    threading.RLock = make_rlock    # type: ignore[assignment]
    try:
        yield g
    finally:
        threading.Lock = orig_lock      # type: ignore[assignment]
        threading.RLock = orig_rlock    # type: ignore[assignment]
