"""JAX program-contract lint: donation safety, jit purity, sharding
specs, and static retrace hazards.

The serving/training stack compiles everything through a small set of
program builders — ``jax.jit`` directly, the mesh-aware ``kv_jit`` /
``kv_shard_map`` wrappers (parallel/serving.py), and the engine's
``_kv_program`` / ``_model_program`` / ``_cached_program`` cache
(serve/server.py). Four contracts gate their performance and
correctness, and all four fail *silently* at runtime — as a recompile
per request, a doubled KV buffer, or bitwise drift — which is exactly
the failure class static lint is for:

* **donation safety** (`donate-use-after`, `donate-sharding-mismatch`)
  — a buffer passed in a donated position is dead the moment the call
  is issued; reading it afterwards is undefined (XLA may have reused
  the pages). And a donated jit whose out_shardings don't match the
  in_shardings on the donated argument silently *drops* the donation:
  GSPMD has to materialize a relaid-out copy, so the engine pays the
  full cache allocation it thought it had donated away.
* **jit purity** (`jit-impure-call`) — a reachability fixpoint from
  every function handed to a jit/shard_map family builder flags host
  effects in traced code: ``time.*``, ``os.environ`` / ``env_*``,
  ``REGISTRY`` metrics, ``FAULTS.fire``, ``print``, lock acquisition,
  the stdlib ``random`` module. A host effect inside a traced body
  runs once per *trace*, not once per call — wrong if the caller meant
  per-call, and a silent no-op after the first trace if they meant
  always. Deliberate trace-time accounting (the kernel wrappers'
  per-trace dispatch counters) is annotated in place with
  ``# lint: jit-impure-ok``.
* **sharding contract** (`sharding-axis-unknown`,
  `shardmap-arity-mismatch`, `kv-axis-pin`) — every ``PartitionSpec``
  axis literal must be in the mesh-axis vocabulary harvested from the
  package's module-level ``MESH_AXES``; shard_map ``in_specs`` arity
  must fit the wrapped function's signature; and ``kv_partition_spec``
  must keep the kv-heads logical axis at index 2 — the one KV-storage
  sharding rule every cache array in models/decode.py is shaped
  around.
* **retrace hazards** (`retrace-captured-scalar`,
  `retrace-static-argnums`, `retrace-mutable-default`) — a jit built
  over a closure that captures the enclosing function's *parameters*
  and is then called in the same body compiles fresh on every
  invocation (the captured scalar is baked into the trace);
  ``static_argnums`` / ``static_argnames`` that don't fit the wrapped
  signature mean the cache keys on the wrong thing; a mutable default
  in a program-builder signature aliases state across builds.

The runtime half is :mod:`tpu_kubernetes.analysis.retrace`
(``TPU_K8S_RETRACE=1``, ``make jax-check``): this pass proves the
*shape* of the program set is sane; the sentinel proves no program
actually compiles twice in steady state.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path

from tpu_kubernetes.analysis import (
    Finding,
    Project,
    call_name,
    literal_str_seq,
)
from tpu_kubernetes.analysis.callresolve import (
    CallIndex,
    FuncRef,
    ModuleInfo,
)

PRAGMA = "lint: jit-impure-ok"

# builders whose first argument is traced ("arg 0"), and the engine's
# program-cache methods whose *second* positional argument is (the
# first is the cache key)
JIT_BUILDERS = {
    "jax.jit": 0, "jit": 0, "kv_jit": 0, "kv_shard_map": 0,
    "shard_map": 0, "shard_map_compat": 0, "jax.shard_map": 0,
    "_kv_program": 1, "_model_program": 1,
}
# the subset that actually *compiles* per builder object — what the
# retrace-captured-scalar rule cares about (plain shard_map only traces
# inside an enclosing jit)
COMPILING_BUILDERS = ("jax.jit", "jit", "kv_jit", "kv_shard_map")

# host-effect call prefixes: first dotted segment → hazard family
IMPURE_ROOTS = {
    "time": "time.*",
    "random": "the stdlib random module",
    "os": None,          # os.environ only — see _impure_reason
    "REGISTRY": "a REGISTRY metric",
    "FAULTS": None,      # FAULTS.fire only
    "print": "print",
}
ENV_HELPERS = ("env_bool", "env_int", "env_float", "env_str")


def run(project: Project) -> list[Finding]:
    index = CallIndex(project)
    axes = _mesh_axis_vocab(project)
    out: list[Finding] = []
    out.extend(_check_donation(project, index))
    out.extend(_check_purity(project, index))
    out.extend(_check_sharding(project, index, axes))
    out.extend(_check_retrace(project, index))
    return out


# -- shared helpers --------------------------------------------------------


def _builder_call(node: ast.Call) -> tuple[str, int] | None:
    """(builder name, traced-arg index) when ``node`` invokes a jit
    family builder, else None. Matches on the final attribute so
    ``self._jax.jit`` and ``st._kv_program`` resolve too."""
    name = call_name(node)
    last = name.split(".")[-1]
    for builder, arg in JIT_BUILDERS.items():
        if name == builder or last == builder.split(".")[-1]:
            return builder, arg
    return None


def _traced_target(node: ast.Call) -> ast.AST | None:
    """The function expression a builder call traces: its positional
    arg at the builder's traced index, unwrapping functools.partial."""
    hit = _builder_call(node)
    if hit is None:
        return None
    _, idx = hit
    if len(node.args) <= idx:
        return None
    target = node.args[idx]
    if isinstance(target, ast.Call) \
            and call_name(target).split(".")[-1] == "partial" \
            and target.args:
        return target.args[0]
    return target


def _int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    """A literal int, or tuple/list of literal ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)
                    and not isinstance(el.value, bool)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _kwarg(node: ast.Call, *names: str) -> ast.AST | None:
    for kw in node.keywords:
        if kw.arg in names:
            return kw.value
    return None


def _expr_path(node: ast.AST) -> str | None:
    """A stable textual path for a Name or dotted attribute chain
    (``cache``, ``self._cache``), else None."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _functions(tree: ast.Module):
    """Yield (funcdef, enclosing class name or None) for every def in a
    module, at any nesting depth."""
    def walk(node: ast.AST, cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)
    yield from walk(tree, None)


def _own_body_walk(fn: ast.AST):
    """Walk a function's body WITHOUT descending into nested defs or
    lambdas (their execution is deferred — a different scope). Document
    order: the donation pass registers a donated program before it sees
    the call that kills the buffer."""
    stack = list(ast.iter_child_nodes(fn))[::-1]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(list(ast.iter_child_nodes(node))[::-1])


def _param_names(fn: ast.AST) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _positional_arity(fn: ast.AST, drop_self: bool = True,
                      bound_kw: set[str] | None = None,
                      ) -> tuple[int, int]:
    """(required, maximum) positional arity of a def, minus any
    keyword-bound params (a functools.partial's keywords)."""
    a = fn.args
    pos = [p.arg for p in a.posonlyargs + a.args]
    if drop_self and pos and pos[0] in ("self", "cls"):
        pos = pos[1:]
    n_default = len(a.defaults)
    required = [p for p in pos[:len(pos) - n_default]]
    optional = [p for p in pos[len(pos) - n_default:]]
    if bound_kw:
        required = [p for p in required if p not in bound_kw]
        optional = [p for p in optional if p not in bound_kw]
    if a.vararg is not None:
        return len(required), 10 ** 6
    return len(required), len(required) + len(optional)


# -- pass 1: donation safety ----------------------------------------------


def _donated_indices(node: ast.Call) -> tuple[int, ...] | None:
    val = _kwarg(node, "donate_argnums", "donate")
    if val is None:
        return None
    idxs = _int_tuple(val)
    if not idxs:
        return None
    return idxs


def _check_donation(project: Project, index: CallIndex) -> list[Finding]:
    out: list[Finding] = []
    for path in project.py_files():
        tree = project.parse(path)
        rel = project.rel(path)
        for fn, _cls in _functions(tree):
            out.extend(_donation_in_function(fn, rel))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                out.extend(_donation_sharding(node, rel))
    return out


def _donation_in_function(fn: ast.AST, rel: str) -> list[Finding]:
    """Flag reads of a variable after it was passed in a donated
    position of a locally-built donated program. Lexical, line-ordered
    approximation: a later store to the same path clears the taint
    (the engine's ``self._cache = ins(self._cache, ...)`` idiom)."""
    donated_programs: dict[str, tuple[int, ...]] = {}
    # path -> (donate line, program name); cleared on reassignment
    dead: dict[str, tuple[int, str]] = {}
    stores: dict[str, list[int]] = {}
    reads: dict[str, list[int]] = {}

    for node in _own_body_walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            idxs = _donated_indices(node.value)
            if idxs is not None and _builder_call(node.value) is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donated_programs[t.id] = idxs
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                p = _expr_path(t)
                if p is not None:
                    stores.setdefault(p, []).append(node.lineno)
        if isinstance(node, ast.Call):
            prog = _expr_path(node.func)
            if prog in donated_programs:
                for i in donated_programs[prog]:
                    if i < len(node.args):
                        p = _expr_path(node.args[i])
                        if p is not None:
                            dead.setdefault(p, (node.lineno, prog))
        if isinstance(node, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            p = _expr_path(node)
            if p is not None:
                reads.setdefault(p, []).append(node.lineno)

    out: list[Finding] = []
    for p, (line, prog) in dead.items():
        revived = [ln for ln in stores.get(p, []) if ln >= line]
        kill = min(revived) if revived else None
        bad = [ln for ln in reads.get(p, [])
               if ln > line and (kill is None or ln < kill)]
        if bad:
            out.append(Finding(
                "donate-use-after", rel, min(bad),
                f"{fn.name}.{p}",
                f"{p} was donated to {prog}() on line {line} and read "
                f"here — the buffer may already be reused by XLA; "
                f"rebind the program's result instead",
            ))
    return out


def _donation_sharding(node: ast.Call, rel: str) -> list[Finding]:
    """Donated jit with literal in/out shardings: the donated arg's
    in_sharding must appear among the out_shardings, or XLA drops the
    donation and the engine silently double-buffers."""
    hit = _builder_call(node)
    if hit is None or hit[0] not in ("jax.jit", "jit"):
        return []
    idxs = _donated_indices(node)
    in_sh = _kwarg(node, "in_shardings")
    out_sh = _kwarg(node, "out_shardings")
    if idxs is None or not isinstance(in_sh, (ast.Tuple, ast.List)) \
            or out_sh is None:
        return []
    out_elts = out_sh.elts if isinstance(out_sh, (ast.Tuple, ast.List)) \
        else [out_sh]
    out_dumps = {ast.dump(e) for e in out_elts}
    findings = []
    for i in idxs:
        if i >= len(in_sh.elts):
            continue
        if ast.dump(in_sh.elts[i]) not in out_dumps:
            findings.append(Finding(
                "donate-sharding-mismatch", rel, node.lineno,
                f"donate_argnums[{i}]",
                f"argument {i} is donated but its in_sharding has no "
                f"matching out_sharding — XLA silently drops the "
                f"donation and re-materializes the buffer",
            ))
    return findings


# -- pass 2: jit purity ----------------------------------------------------


def _impure_reason(name: str, metric_objects: set[str]) -> str | None:
    parts = name.split(".")
    root = parts[0]
    if name == "print":
        return "print writes to the host once per trace"
    if root == "time":
        return "time.* reads the host clock at trace time"
    if root == "random":
        return "stdlib random draws host entropy at trace time " \
               "(use jax.random)"
    if root == "os" and len(parts) >= 2 and parts[1] == "environ":
        return "os.environ is read at trace time, not per call"
    if root in ENV_HELPERS:
        return f"{root}() reads the environment at trace time"
    if root == "REGISTRY" or root in metric_objects:
        return "metric updates in traced code run once per trace, " \
               "not per call"
    if root == "FAULTS" and len(parts) >= 2 and parts[1] == "fire":
        return "FAULTS.fire in traced code fires per trace, not per call"
    if len(parts) >= 2 and parts[-1] == "acquire":
        return "lock acquisition in traced code guards the trace, " \
               "not the execution"
    return None


def _metric_objects(project: Project, index: CallIndex) -> set[str]:
    """Module-level names bound to REGISTRY factories (counters,
    gauges, histograms) — calls on them are REGISTRY effects."""
    names: set[str] = set()
    for path in project.py_files():
        tree = project.parse(path)
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and call_name(node.value).startswith("REGISTRY."):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _purity_entries(project: Project, index: CallIndex):
    """Yield (FuncRef, entry description) for every function object
    handed to a jit/shard_map family builder anywhere in the package,
    plus inline lambdas as (lambda node, module) pairs."""
    for path in project.py_files():
        tree = project.parse(path)
        mod = index.module_of(path)
        if mod is None:
            continue
        for fn, cls in _functions(tree):
            local_defs = {
                n.name: n for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            }
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                target = _traced_target(node)
                if target is None:
                    continue
                if isinstance(target, ast.Lambda):
                    yield ("lambda", target, mod, cls,
                           f"{fn.name}:<lambda>")
                elif isinstance(target, ast.Name):
                    if target.id in local_defs:
                        yield ("def", local_defs[target.id], mod, cls,
                               f"{fn.name}.{target.id}")
                    else:
                        ref = index.resolve(target.id, mod, cls)
                        if ref is not None:
                            yield ("ref", ref, mod, cls, ref.qualname)
                elif isinstance(target, ast.Attribute):
                    name = _expr_path(target)
                    if name is not None:
                        ref = index.resolve(name, mod, cls)
                        if ref is not None:
                            yield ("ref", ref, mod, cls, ref.qualname)


def _check_purity(project: Project, index: CallIndex) -> list[Finding]:
    metric_objects = _metric_objects(project, index)
    lines_cache: dict[Path, list[str]] = {}

    def src_lines(path: Path) -> list[str]:
        if path not in lines_cache:
            lines_cache[path] = path.read_text(
                encoding="utf-8").splitlines()
        return lines_cache[path]

    out: list[Finding] = []
    seen_findings: set[tuple[str, int]] = set()
    visited: set[int] = set()       # id() of scanned function nodes

    def scan(fn_node: ast.AST, mod: ModuleInfo, cls: str | None,
             entry: str, depth: int) -> None:
        if id(fn_node) in visited or depth > 12:
            return
        visited.add(id(fn_node))
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            reason = _impure_reason(name, metric_objects)
            if reason is not None:
                src = src_lines(mod.path)
                text = src[node.lineno - 1] \
                    if node.lineno <= len(src) else ""
                if PRAGMA in text:
                    continue
                key = (mod.rel, node.lineno)
                if key not in seen_findings:
                    seen_findings.add(key)
                    out.append(Finding(
                        "jit-impure-call", mod.rel, node.lineno,
                        f"{entry}:{name}",
                        f"{name}() is reachable from jitted entry "
                        f"{entry}: {reason}",
                    ))
                continue
            # skip jax/numpy internals; follow package calls only
            root = name.split(".")[0]
            if root in ("jax", "jnp", "lax", "np", "numpy", "functools"):
                continue
            ref = index.resolve(name, mod, cls)
            if ref is not None:
                nxt_mod = index.module_of(ref.path)
                nxt_cls = ref.qualname.split(".")[0] \
                    if "." in ref.qualname else None
                if nxt_mod is not None:
                    scan(ref.node, nxt_mod, nxt_cls, entry, depth + 1)

    for kind, node, mod, cls, entry in _purity_entries(project, index):
        if kind == "ref":
            ref: FuncRef = node
            ref_mod = index.module_of(ref.path)
            ref_cls = ref.qualname.split(".")[0] \
                if "." in ref.qualname else None
            if ref_mod is not None:
                scan(ref.node, ref_mod, ref_cls, entry, 0)
        else:
            scan(node, mod, cls, entry, 0)
    return sorted(out, key=lambda f: (f.path, f.line))


# -- pass 3: sharding contract --------------------------------------------


def _mesh_axis_vocab(project: Project) -> set[str] | None:
    """The closed mesh-axis vocabulary: the union of every module-level
    ``MESH_AXES = (...)`` literal in the package (parallel/mesh.py on
    the real tree). None when the package declares no vocabulary — the
    axis check then has nothing to enforce."""
    axes: set[str] = set()
    found = False
    for path in project.py_files():
        tree = project.parse(path)
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "MESH_AXES":
                        vals = literal_str_seq(node.value)
                        if vals is not None:
                            axes.update(vals)
                            found = True
    return axes if found else None


def _pspec_aliases(mod: ModuleInfo) -> set[str]:
    """Local names that refer to jax.sharding.PartitionSpec."""
    names = {"PartitionSpec"}
    for local, (src, orig) in mod.from_imports.items():
        if orig == "PartitionSpec":
            names.add(local)
    return names


def _spec_axis_literals(node: ast.Call):
    """Yield (axis string, line) for literal axis names in a
    PartitionSpec call: direct string args and elements of literal
    tuple/list args (incl. starred literals). Computed expressions are
    skipped — only literals are checkable."""
    def from_elts(elts):
        for el in elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                yield el.value, el.lineno

    args = []
    for a in node.args:
        args.append(a.value if isinstance(a, ast.Starred) else a)
    for a in args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            yield a.value, a.lineno
        elif isinstance(a, (ast.Tuple, ast.List)):
            yield from from_elts(a.elts)


def _check_sharding(project: Project, index: CallIndex,
                    axes: set[str] | None) -> list[Finding]:
    out: list[Finding] = []
    for path in project.py_files():
        tree = project.parse(path)
        rel = project.rel(path)
        mod = index.module_of(path)
        if mod is None:
            continue
        aliases = _pspec_aliases(mod)
        for fn, cls in _functions(tree):
            if fn.name == "kv_partition_spec":
                out.extend(_check_kv_pin(fn, rel))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if axes is not None and name.split(".")[-1] in aliases:
                for axis, line in _spec_axis_literals(node):
                    if axis not in axes:
                        out.append(Finding(
                            "sharding-axis-unknown", rel, line, axis,
                            f"PartitionSpec axis {axis!r} is not in the "
                            f"mesh-axis vocabulary "
                            f"({', '.join(sorted(axes))})",
                        ))
            out.extend(_check_shardmap_arity(node, rel, index, mod))
    return out


def _check_kv_pin(fn: ast.AST, rel: str) -> list[Finding]:
    """kv_partition_spec must keep the ``kv`` logical axis at index 2 —
    the axis-2 kv-heads pin every cache array in models/decode.py is
    documented against."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and call_name(node).split(".")[-1] == "logical_to_spec" \
                and node.args \
                and isinstance(node.args[0], (ast.Tuple, ast.List)):
            elts = node.args[0].elts
            kv_at = [
                i for i, el in enumerate(elts)
                if isinstance(el, ast.Constant) and el.value == "kv"
            ]
            if kv_at != [2]:
                where = kv_at[0] if kv_at else None
                return [Finding(
                    "kv-axis-pin", rel, node.lineno, "kv_partition_spec",
                    f"kv_partition_spec places the 'kv' logical axis at "
                    f"index {where} — KV storage keeps kv-heads at axis "
                    f"2 (models/decode.py cache layout contract)",
                )]
            return []
    return []


def _check_shardmap_arity(node: ast.Call, rel: str, index: CallIndex,
                          mod: ModuleInfo) -> list[Finding]:
    name = call_name(node).split(".")[-1]
    if name not in ("shard_map", "shard_map_compat"):
        return []
    in_specs = _kwarg(node, "in_specs")
    if in_specs is None and len(node.args) >= 3:
        in_specs = node.args[2]
    if not isinstance(in_specs, (ast.Tuple, ast.List)) or not node.args:
        return []
    n_specs = len(in_specs.elts)
    target = node.args[0]
    bound_kw: set[str] = set()
    if isinstance(target, ast.Call) \
            and call_name(target).split(".")[-1] == "partial" \
            and target.args:
        bound_kw = {kw.arg for kw in target.keywords if kw.arg}
        target = target.args[0]
    fn_node = None
    if isinstance(target, ast.Name):
        ref = index.resolve(target.id, mod)
        if ref is not None and isinstance(
                ref.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_node = ref.node
    elif isinstance(target, ast.Lambda):
        fn_node = target
    if fn_node is None:
        return []
    lo, hi = _positional_arity(fn_node, bound_kw=bound_kw)
    if lo <= n_specs <= hi:
        return []
    want = str(lo) if lo == hi else f"{lo}..{hi}"
    fname = getattr(fn_node, "name", "<lambda>")
    return [Finding(
        "shardmap-arity-mismatch", rel, node.lineno, fname,
        f"in_specs has {n_specs} entries but {fname} takes {want} "
        f"positional argument(s)",
    )]


# -- pass 4: retrace hazards ----------------------------------------------


def _free_names(fn: ast.AST) -> set[str]:
    """Names loaded in a function body that it neither binds as a
    parameter nor assigns locally — its closure reads."""
    bound = set(_param_names(fn)) | {"self", "cls"}
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    loads: set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    bound.add(node.id)
                elif isinstance(node.ctx, ast.Load):
                    loads.add(node.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                bound.add(node.name)
    return loads - bound


@dataclass
class _JitBuild:
    node: ast.Call
    target: ast.AST
    assigned: str | None


def _check_retrace(project: Project, index: CallIndex) -> list[Finding]:
    out: list[Finding] = []
    for path in project.py_files():
        tree = project.parse(path)
        rel = project.rel(path)
        mod = index.module_of(path)
        for fn, cls in _functions(tree):
            out.extend(_retrace_in_function(fn, rel, index, mod))
            out.extend(_mutable_defaults(fn, rel))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                out.extend(_static_argnums(node, rel, index, mod))
    return out


def _retrace_in_function(fn: ast.AST, rel: str, index: CallIndex,
                         mod: ModuleInfo | None) -> list[Finding]:
    """A jit built in this body over a closure capturing this
    function's parameters, then *called* in this body (not returned,
    not deferred into a nested def, not cached) — compiles fresh per
    invocation with the captured scalar baked in."""
    params = set(_param_names(fn))
    if not params:
        return []
    local_defs = {}
    builds: list[_JitBuild] = []
    returned: set[str] = set()
    called: dict[str, int] = {}
    nested_refs: set[str] = set()
    stored_away: set[str] = set()

    for node in _own_body_walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs[node.name] = node
            for name in _free_names(node):
                nested_refs.add(name)
        elif isinstance(node, ast.Lambda):
            for name in _free_names(node):
                nested_refs.add(name)
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    returned.add(sub.id)
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call) \
                    and _builder_call(node.value) is not None \
                    and _builder_call(node.value)[0] in \
                    COMPILING_BUILDERS:
                target = _traced_target(node.value)
                if target is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            builds.append(_JitBuild(
                                node.value, target, t.id))
                        else:
                            # stored into a cache dict / attribute:
                            # a keyed program, not a per-call rebuild
                            pass
        elif isinstance(node, ast.Call):
            prog = _expr_path(node.func)
            if prog is not None and "." not in prog:
                called.setdefault(prog, node.lineno)

    out: list[Finding] = []
    for b in builds:
        if b.assigned is None or b.assigned not in called:
            continue
        if b.assigned in returned or b.assigned in nested_refs \
                or b.assigned in stored_away:
            continue
        if isinstance(b.target, ast.Lambda):
            captured = sorted(_free_names(b.target) & params)
            tname = "<lambda>"
        elif isinstance(b.target, ast.Name) \
                and b.target.id in local_defs:
            captured = sorted(
                _free_names(local_defs[b.target.id]) & params)
            tname = b.target.id
        else:
            continue    # module function or parameter: no capture
        if not captured:
            continue
        out.append(Finding(
            "retrace-captured-scalar", rel, b.node.lineno,
            f"{fn.name}.{b.assigned}",
            f"{b.assigned} jits {tname} which captures per-call "
            f"parameter(s) {', '.join(captured)} and is called in the "
            f"same body — every invocation re-traces; key a cached "
            f"program on the captured value instead",
        ))
    return out


def _static_argnums(node: ast.Call, rel: str, index: CallIndex,
                    mod: ModuleInfo | None) -> list[Finding]:
    hit = _builder_call(node)
    if hit is None or hit[0] not in ("jax.jit", "jit"):
        return []
    target = _traced_target(node)
    fn_node = None
    if isinstance(target, ast.Name) and mod is not None:
        ref = index.resolve(target.id, mod)
        if ref is not None and isinstance(
                ref.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_node = ref.node
    elif isinstance(target, ast.Lambda):
        fn_node = target
    if fn_node is None:
        return []
    out: list[Finding] = []
    nums = _kwarg(node, "static_argnums")
    if nums is not None:
        idxs = _int_tuple(nums)
        if idxs is not None:
            _lo, hi = _positional_arity(fn_node)
            bad = [i for i in idxs if i < 0 or (hi < 10 ** 6
                                               and i >= hi)]
            if bad:
                fname = getattr(fn_node, "name", "<lambda>")
                out.append(Finding(
                    "retrace-static-argnums", rel, node.lineno, fname,
                    f"static_argnums {bad} out of range for {fname} "
                    f"({hi} positional argument(s)) — the cache keys "
                    f"on nothing and every call may retrace",
                ))
    names = _kwarg(node, "static_argnames")
    if names is not None:
        vals = literal_str_seq(names)
        if vals is None and isinstance(names, ast.Constant) \
                and isinstance(names.value, str):
            vals = [names.value]
        if vals is not None:
            have = set(_param_names(fn_node))
            bad_names = [v for v in vals if v not in have]
            if bad_names:
                fname = getattr(fn_node, "name", "<lambda>")
                out.append(Finding(
                    "retrace-static-argnums", rel, node.lineno, fname,
                    f"static_argnames {bad_names} not parameters of "
                    f"{fname}",
                ))
    return out


def _mutable_defaults(fn: ast.AST, rel: str) -> list[Finding]:
    """Mutable default in a program-builder signature: the default is
    evaluated once and aliased across every build."""
    has_builder = any(
        isinstance(n, ast.Call) and _builder_call(n) is not None
        for n in ast.walk(fn)
    )
    if not has_builder:
        return []
    out: list[Finding] = []
    a = fn.args
    for p, default in zip(
            (a.posonlyargs + a.args)[-len(a.defaults):]
            if a.defaults else [], a.defaults):
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            out.append(Finding(
                "retrace-mutable-default", rel, default.lineno,
                f"{fn.name}.{p.arg}",
                f"mutable default for {p.arg!r} in program builder "
                f"{fn.name}() is shared across every build",
            ))
    for p, default in zip(a.kwonlyargs, a.kw_defaults):
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            out.append(Finding(
                "retrace-mutable-default", rel, default.lineno,
                f"{fn.name}.{p.arg}",
                f"mutable default for {p.arg!r} in program builder "
                f"{fn.name}() is shared across every build",
            ))
    return out
