"""Repo-native invariant analyzer: contract lint + concurrency passes.

The stack rests on closed vocabularies and concurrency discipline that
review alone cannot enforce: the fault-site vocabulary (obs/faults.py
``SITES``), the ``tpu_*`` metric naming scheme and the observability-doc
metric catalog, the ledger settlement classes (obs/ledger.py
``CLASSES``), the alert-rule kind registry (obs/alerts.py
``RULE_KINDS``), the ``TPU_K8S_*``/``SERVE_*``/``SERVER_*`` env
contract, and the hand-audited ``with self._lock`` regions guarding the
scheduler / page pool / aggregator / notifier. Each of those has
regressed silently at least once (the spec_totals lock fix, SLO
flapping, counter-reset clamps); this package makes them *mechanical*:

* **AST passes** (:mod:`contracts`, :mod:`envcontract`,
  :mod:`concurrency`, :mod:`jaxcontract`) lint the package source
  without importing it — no jax, no side effects, fast enough for a
  pre-commit hook. :mod:`jaxcontract` covers the JAX program
  contracts: donation safety, jit purity (an interprocedural
  reachability fixpoint over :mod:`callresolve`), PartitionSpec /
  shard_map sharding specs, and static retrace hazards.
* **A runtime lock-order watchdog** (:mod:`lockgraph`) instruments
  ``threading.Lock`` during the chaos/resilience suites, builds the
  cross-thread lock-acquisition graph, and fails the run on a cycle.
* **A runtime retrace sentinel** (:mod:`retrace`) instruments
  ``jax.jit`` during the serve-identity suites (``TPU_K8S_RETRACE=1``,
  ``make jax-check``) and fails any test where one compiled program
  traces twice for the same input signature.

Surfaces: ``tpu-kubernetes analyze [--json] [--pass NAME]
[--update-baseline]`` and ``make analysis-check`` (exits non-zero on
findings not in the committed baseline, ``analysis-baseline.json`` —
intentionally empty on the shipped tree). docs/guide/static-analysis.md
documents every finding code and the baseline workflow.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable

# one entry per finding code — docs/guide/static-analysis.md mirrors
# this table; the fixture suite asserts each code is producible
FINDING_CODES = {
    "fault-site-unknown":
        "FAULTS.fire() literal not in the obs/faults.py SITES vocabulary",
    "fault-site-unfired":
        "SITES entry with no FAULTS.fire() call site anywhere in the "
        "package (a chaos site that can never fire is a lie)",
    "fault-site-dynamic":
        "FAULTS.fire() with a non-literal site (the closed vocabulary "
        "cannot be checked through a variable)",
    "metric-name-scheme":
        "registered metric name is dynamic or does not match the "
        "tpu_[a-z0-9_]* naming scheme",
    "metric-labels-not-literal":
        "labelnames= is not a literal tuple/list of string literals",
    "metric-unregistered":
        "metric named in the docs tables / alerts.d rules / monitor "
        "columns resolves to no registered metric",
    "metric-undocumented":
        "registered metric missing from the "
        "docs/guide/observability.md catalog",
    "ledger-class-unknown":
        "ledger settle() literal not in the obs/ledger.py CLASSES "
        "vocabulary",
    "alert-kind-unknown":
        "alerts.d rule kind not registered via @rule_kind",
    "action-kind-unknown":
        "new_action() literal not in the obs/controller.py ACTION_KINDS "
        "vocabulary",
    "action-kind-undocumented":
        "ACTION_KINDS entry missing from the docs/guide/observability.md "
        "action table (an undocumented remediation is an unauditable one)",
    "env-undocumented":
        "TPU_K8S_*/SERVE_*/SERVER_* env read with no docs-table or "
        "module-docstring row",
    "env-stale-doc":
        "documented env var that nothing in the package or tests reads",
    "lock-unguarded-write":
        "write to lock-guarded shared state outside a `with self._lock` "
        "region",
    "lock-blocking-call":
        "blocking call (sleep / urlopen / subprocess / terraform exec) "
        "made while a lock is held",
    "donate-use-after":
        "variable read after being passed in a donated position of a "
        "jit/kv_jit/kv_shard_map program (the buffer may be reused)",
    "donate-sharding-mismatch":
        "donated jit whose out_shardings don't match in_shardings on "
        "the donated argument — XLA silently drops the donation",
    "jit-impure-call":
        "host effect (time/env/metrics/print/locks/random) reachable "
        "from a function handed to jit/shard_map — runs per trace, "
        "not per call",
    "sharding-axis-unknown":
        "PartitionSpec axis literal not in the package's MESH_AXES "
        "mesh-axis vocabulary",
    "shardmap-arity-mismatch":
        "shard_map in_specs arity doesn't fit the wrapped function's "
        "positional signature",
    "kv-axis-pin":
        "kv_partition_spec moved the 'kv' logical axis off index 2 "
        "(the KV-storage axis-2 kv-heads layout contract)",
    "retrace-captured-scalar":
        "jit over a closure capturing per-call parameters, called in "
        "the same body — recompiles on every invocation",
    "retrace-static-argnums":
        "static_argnums/static_argnames don't fit the wrapped "
        "function's signature — the compile cache keys on nothing",
    "retrace-mutable-default":
        "mutable default argument in a program-builder signature "
        "(aliased across every build)",
}

PASS_NAMES = ("contracts", "env", "concurrency", "jaxcontract")

JSON_SCHEMA_VERSION = 2


@dataclass(frozen=True)
class Finding:
    """One analyzer hit. ``symbol`` is the stable anchor (site name,
    metric name, env var, ``Class.attr``) the baseline matches on, so a
    baselined exception survives line drift."""

    code: str
    path: str          # repo-root-relative, forward slashes
    line: int
    symbol: str
    message: str
    pass_name: str = ""

    def key(self) -> tuple[str, str, str]:
        return (self.code, self.path, self.symbol)

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "pass": self.pass_name,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
        }


class ProjectError(RuntimeError):
    pass


@dataclass
class Project:
    """What the passes scan: a package tree plus its doc surfaces.

    ``discover()`` resolves the real repo layout; tests point it at the
    violation fixture tree (same conventions, miniature scale)."""

    root: Path
    pkg: Path
    doc_files: list[Path] = field(default_factory=list)
    metric_doc: Path | None = None      # the metric/env catalog doc
    alert_files: list[Path] = field(default_factory=list)
    tests_dir: Path | None = None
    _sources: dict[Path, ast.Module] | None = None

    @classmethod
    def discover(cls, root: str | Path) -> "Project":
        root = Path(root).resolve()
        pkg = root / "tpu_kubernetes"
        if not (pkg / "__init__.py").is_file():
            candidates = sorted(
                p.parent for p in root.glob("*/__init__.py")
                if p.parent.name not in ("tests", "docs")
            )
            if not candidates:
                raise ProjectError(f"no python package under {root}")
            pkg = candidates[0]
        docs = sorted((root / "docs").rglob("*.md")) \
            if (root / "docs").is_dir() else []
        readme = root / "README.md"
        if readme.is_file():
            docs.append(readme)
        metric_doc = next(
            (d for d in docs if d.name == "observability.md"), None
        )
        alerts_dir = root / "examples" / "alerts.d"
        alert_files = sorted(alerts_dir.glob("*.json")) \
            if alerts_dir.is_dir() else []
        tests_dir = root / "tests" if (root / "tests").is_dir() else None
        return cls(root=root, pkg=pkg, doc_files=docs,
                   metric_doc=metric_doc, alert_files=alert_files,
                   tests_dir=tests_dir)

    # -- source access ----------------------------------------------------

    def py_files(self) -> list[Path]:
        return sorted(
            p for p in self.pkg.rglob("*.py")
            if "__pycache__" not in p.parts
        )

    def tests_py_files(self) -> list[Path]:
        """Test-tree sources, excluding ``fixtures/`` — the intentional
        violation packages under tests/fixtures/ must not count as real
        read sites (a repo-root run would otherwise let a fixture env
        read mask a genuinely stale doc row)."""
        if self.tests_dir is None:
            return []
        return sorted(
            p for p in self.tests_dir.rglob("*.py")
            if "__pycache__" not in p.parts
            and "fixtures" not in p.parts
        )

    def parse(self, path: Path) -> ast.Module:
        if self._sources is None:
            self._sources = {}
        tree = self._sources.get(path)
        if tree is None:
            tree = ast.parse(
                path.read_text(encoding="utf-8"), filename=str(path)
            )
            self._sources[path] = tree
        return tree

    def rel(self, path: Path) -> str:
        try:
            return path.resolve().relative_to(self.root).as_posix()
        except ValueError:
            return path.as_posix()

    def doc_text(self) -> str:
        return "\n".join(
            p.read_text(encoding="utf-8") for p in self.doc_files
        )


# -- pass registry ---------------------------------------------------------

def run_pass(project: Project, name: str) -> list[Finding]:
    from tpu_kubernetes.analysis import (
        concurrency,
        contracts,
        envcontract,
        jaxcontract,
    )

    table: dict[str, Callable[[Project], list[Finding]]] = {
        "contracts": contracts.run,
        "env": envcontract.run,
        "concurrency": concurrency.run,
        "jaxcontract": jaxcontract.run,
    }
    if name not in table:
        raise ProjectError(
            f"unknown pass {name!r} (one of {list(PASS_NAMES)})"
        )
    findings = table[name](project)
    return [
        Finding(f.code, f.path, f.line, f.symbol, f.message, name)
        for f in findings
    ]


def run_analysis(root: str | Path, passes: Iterable[str] | None = None,
                 ) -> list[Finding]:
    """Run the requested passes (default: all) over ``root`` and return
    findings sorted by (path, line, code)."""
    findings, _timings = run_analysis_timed(root, passes)
    return findings


def run_analysis_timed(root: str | Path,
                       passes: Iterable[str] | None = None,
                       ) -> tuple[list[Finding], dict[str, float]]:
    """Like :func:`run_analysis`, also returning per-pass wall time in
    seconds (what ``analyze --json`` reports, so analyzer slowdowns
    show up in review)."""
    import time

    project = Project.discover(root)
    out: list[Finding] = []
    timings: dict[str, float] = {}
    for name in (passes or PASS_NAMES):
        t0 = time.perf_counter()
        out.extend(run_pass(project, name))
        timings[name] = round(time.perf_counter() - t0, 6)
    return (sorted(out, key=lambda f: (f.path, f.line, f.code, f.symbol)),
            timings)


# -- baseline --------------------------------------------------------------

BASELINE_NAME = "analysis-baseline.json"


def load_baseline(path: str | Path) -> set[tuple[str, str, str]]:
    """The committed exception list: ``{"suppress": [{code, path,
    symbol}, ...]}``. Missing file = empty baseline (the shipped state);
    a malformed file is a loud error, not a silent all-clear."""
    p = Path(path)
    if not p.is_file():
        return set()
    try:
        data = json.loads(p.read_text(encoding="utf-8"))
    except json.JSONDecodeError as exc:
        raise ProjectError(f"{p}: not valid JSON: {exc}") from exc
    if not isinstance(data, dict):
        raise ProjectError(f"{p}: baseline must be a JSON object")
    entries = data.get("suppress", [])
    if not isinstance(entries, list):
        raise ProjectError(f"{p}: 'suppress' must be a list")
    out = set()
    for e in entries:
        try:
            out.add((e["code"], e["path"], e["symbol"]))
        except (TypeError, KeyError) as exc:
            raise ProjectError(
                f"{p}: baseline entries need code/path/symbol ({e!r})"
            ) from exc
    return out


def write_baseline(path: str | Path, findings: list[Finding]) -> None:
    """Atomically rewrite the baseline from current findings: entries
    sorted and deduplicated by (code, path, symbol), written to a temp
    file and renamed into place so a crashed run can't leave a
    truncated gate file behind."""
    import os

    keys = sorted({f.key() for f in findings})
    entries = [
        {"code": code, "path": p, "symbol": symbol}
        for code, p, symbol in keys
    ]
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(
        json.dumps({"version": 1, "suppress": entries},
                   indent=2, sort_keys=True) + "\n",
        encoding="utf-8",
    )
    os.replace(tmp, path)


def split_baselined(findings: list[Finding],
                    baseline: set[tuple[str, str, str]],
                    ) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined) — baselined findings are reported but do not
    fail the gate."""
    new = [f for f in findings if f.key() not in baseline]
    old = [f for f in findings if f.key() in baseline]
    return new, old


def report_json(findings: list[Finding], baselined: list[Finding],
                root: str, passes: Iterable[str],
                timings: dict[str, float] | None = None) -> dict:
    """The ``analyze --json`` payload — a stable schema monitor-style
    tooling consumes (tests/test_analysis.py pins it). ``timings`` is
    per-pass wall seconds from :func:`run_analysis_timed`."""
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.code] = counts.get(f.code, 0) + 1
    return {
        "version": JSON_SCHEMA_VERSION,
        "root": root,
        "passes": sorted(passes),
        "ok": not findings,
        "counts": counts,
        "timings": dict(sorted((timings or {}).items())),
        "findings": [f.to_dict() for f in findings],
        "baselined": [f.to_dict() for f in baselined],
    }


def render_findings(findings: list[Finding], baselined: list[Finding],
                    ) -> str:
    """Human rendering: one ``path:line: code [symbol] message`` line
    per finding, compiler style, so terminals and CI logs link it."""
    lines = []
    for f in findings:
        lines.append(
            f"{f.path}:{f.line}: {f.code} [{f.symbol}] {f.message}"
        )
    for f in baselined:
        lines.append(
            f"{f.path}:{f.line}: {f.code} [{f.symbol}] (baselined) "
            f"{f.message}"
        )
    if not findings:
        lines.append(
            "analysis clean"
            + (f" ({len(baselined)} baselined)" if baselined else "")
        )
    else:
        lines.append(f"{len(findings)} finding(s)")
    return "\n".join(lines) + "\n"


# -- shared AST helpers (used by every pass) -------------------------------

def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def literal_str_seq(node: ast.AST) -> list[str] | None:
    """A literal tuple/list/set of string constants, or None. Unwraps
    ``frozenset({...})`` / ``set(...)`` / ``tuple(...)`` calls."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set", "tuple", "list") \
            and len(node.args) == 1 and not node.keywords:
        node = node.args[0]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            s = str_const(el)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best-effort: ``time.sleep`` →
    'time.sleep', ``self._lock.acquire`` → 'self._lock.acquire'."""
    parts: list[str] = []
    cur: ast.AST = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        parts.append("()")
    return ".".join(reversed(parts))


ENV_PREFIX_RE = re.compile(r"^(?:TPU_K8S_|SERVE_|SERVER_)[A-Z0-9_]+$")
METRIC_RE = re.compile(r"^tpu_[a-z0-9_]+$")
METRIC_TOKEN_RE = re.compile(r"\btpu_[a-z0-9_]+\b")
ENV_TOKEN_RE = re.compile(r"\b(?:TPU_K8S_|SERVE_|SERVER_)[A-Z0-9_]+\b")
