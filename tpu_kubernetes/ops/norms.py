"""RMSNorm and rotary position embeddings.

Kept as plain jnp on purpose: these are bandwidth-bound elementwise ops that
XLA fuses into their surrounding matmuls — a hand-written Pallas kernel would
only re-derive the fusion XLA already performs (unlike attention, where the
O(seq²) intermediate forces the flash restructuring in
ops/flash_attention.py). Computation runs in float32 and casts back, the
standard recipe for bf16 training stability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """LLaMA-style RMSNorm: x * rsqrt(mean(x²)) * weight."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(
    head_dim: int, max_seq: int, theta: float = 10000.0
) -> tuple[jax.Array, jax.Array]:
    """Precompute cos/sin tables of shape (max_seq, head_dim // 2)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array | None = None
) -> jax.Array:
    """Rotate pairs of channels. x: (batch, heads, seq, head_dim);
    cos/sin: (max_seq, head_dim//2); positions: (seq,) shared across the
    batch, (batch, seq) per-row (ragged serving batches), or None for
    0..seq-1."""
    seq = x.shape[2]
    if positions is None:
        cos_t, sin_t = cos[:seq], sin[:seq]
    else:
        cos_t, sin_t = cos[positions], sin[positions]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if positions is not None and positions.ndim == 2:
        cos_t = cos_t[:, None, :, :]         # (b, 1, seq, hd/2)
        sin_t = sin_t[:, None, :, :]
    else:
        cos_t = cos_t[None, None, :, :]
        sin_t = sin_t[None, None, :, :]
    rotated = jnp.concatenate(
        [x1 * cos_t - x2 * sin_t, x1 * sin_t + x2 * cos_t], axis=-1
    )
    return rotated.astype(x.dtype)
