"""Grouped (ragged) matmul as a Pallas TPU kernel — the dropless-MoE
expert GEMM (fwd + bwd, custom VJP).

``grouped_matmul(lhs, rhs, group_sizes)`` multiplies contiguous row groups
of ``lhs`` (M, K) against per-group weights ``rhs`` (E, K, N): rows
``[off_e, off_{e+1})`` (offsets = cumulative group sizes) go through
``rhs[e]``. This is the MegaBlocks-shaped primitive behind
``dispatch_mode="grouped"`` in models/moe.py: sort tokens by expert, run
ONE kernel whose grid walks (expert, row-block) pairs — no expert-capacity
padding, no dropped tokens, and the group boundary handling lives in the
kernel instead of a (b, s, E, C) dispatch tensor.

TPU design:

* **Static shapes.** Group sizes are data-dependent VALUES but every array
  shape is static: the tile enumeration runs as traced integer ops whose
  results feed the kernel through scalar prefetch (SMEM), and the worst
  case — every group boundary splitting a row block — bounds the grid at
  ``M/block_m + E - 1`` tiles.
* **Grid (N-blocks, tiles, K-blocks)**, so the (tile, K-block) steps
  covering one output row-block are adjacent: partial products accumulate
  in an f32 VMEM scratch and are written once, when the last tile's last
  K-block retires. K is tiled (``block_k``) so VMEM residency never
  scales with the full contraction dim — mixtral-8x7b's d_ff=14336
  stays a few hundred KB per block, not a 14 MB operand.
* Row→group membership is enforced by masking lhs rows against the group's
  offset range before the dot, so a block spanning a boundary contributes
  each row to exactly one group. All matmuls accumulate in float32 on the
  MXU (``preferred_element_type``).
* Backward reuses the machinery: dlhs = grouped_matmul(dout, rhsᵀ) (same
  kernel, swapped operands); drhs accumulates lhs-blockᵀ @ dout-block per
  group in a second kernel with the same tile enumeration.

A plain-XLA reference (``grouped_matmul_reference``) is the correctness
oracle in tests (the kernel runs in interpret mode on CPU) and the
fallback on non-TPU backends.

The reference provisioner has no ML code; this op belongs to the in-tree
training stack's MoE family (SURVEY.md §2.7).
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from tpu_kubernetes.ops.flash_attention import OPS_TRACED, _fit_block, _on_tpu

try:  # the grid spec + scratch spaces here genuinely need pltpu (unlike
    # flash_attention, whose specs degrade to plain BlockSpec); without it
    # every path falls back to the XLA reference
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_K = 512

_warned_tpu_fallback = False


def _fit_block_div(block: int, dim: int) -> int:
    """Largest multiple-of-128 divisor of ``dim`` that is ≤ ``block``.
    Requires dim % 128 == 0 (the public entry enforces it), so 128 always
    qualifies — unlike halving, this can never hand back a non-divisor
    that would silently truncate a grid."""
    for c in range(min(block, dim) // 128, 0, -1):
        if dim % (128 * c) == 0:
            return 128 * c
    return 128


def _int_zeros(a):
    """Symbolic-zero cotangent for an integer primal."""
    return np.zeros(a.shape, jax.dtypes.float0)


# --------------------------------------------------------------------------
# reference (XLA) implementation — oracle + non-TPU fallback
# --------------------------------------------------------------------------

def grouped_matmul_reference(
    lhs: jax.Array, rhs: jax.Array, group_sizes: jax.Array
) -> jax.Array:
    """Plain-XLA grouped matmul: E full matmuls with row masks, summed.
    O(E·M·K·N) flops — fine at test shapes and as the CPU fallback; the
    Pallas kernel is the TPU path."""
    m = lhs.shape[0]
    off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes, dtype=jnp.int32)]
    )
    rows = jnp.arange(m, dtype=jnp.int32)
    gid = jnp.searchsorted(off[1:], rows, side="right").astype(jnp.int32)
    valid = rows < off[-1]

    def step(acc, xs):
        w, e = xs
        sel = ((gid == e) & valid)[:, None]
        prod = jnp.dot(lhs, w, preferred_element_type=jnp.float32)
        return acc + jnp.where(sel, prod, 0.0), None

    acc = jnp.zeros((m, rhs.shape[-1]), jnp.float32)
    acc, _ = jax.lax.scan(
        step, acc, (rhs, jnp.arange(rhs.shape[0], dtype=jnp.int32))
    )
    return acc.astype(lhs.dtype)


# --------------------------------------------------------------------------
# tile enumeration (traced; feeds the kernels via scalar prefetch)
# --------------------------------------------------------------------------

# rows of the (7, T) tile-metadata array
_ROW, _GRP, _FIRST_ROW, _LAST_ROW, _FIRST_GRP, _LAST_GRP, _ACTIVE = range(7)


def _tile_metadata(group_sizes: jax.Array, n_rows: int, bm: int):
    """Enumerate (row-block, group) intersection tiles, sorted by (group,
    row). Static tile count T = n_rows/bm + E - 1 (worst case: every group
    boundary splits a block); unused tail tiles are flagged inactive and
    mapped onto the final block so they never trigger a buffer flush of an
    unwritten block. Returns (tiles (7, T) int32, offsets (E+1,) int32)."""
    e = group_sizes.shape[0]
    mb = n_rows // bm
    t_static = mb + e - 1
    off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes, dtype=jnp.int32)]
    )
    nonempty = group_sizes > 0
    fb = off[:-1] // bm                                   # first block of g
    lb = jnp.where(nonempty, (off[1:] - 1) // bm, 0)      # last block of g
    ntiles = jnp.where(nonempty, lb - fb + 1, 0)
    ts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(ntiles, dtype=jnp.int32)]
    )

    tt = jnp.arange(t_static, dtype=jnp.int32)
    active = tt < ts[-1]
    g = jnp.clip(
        jnp.searchsorted(ts[1:], tt, side="right").astype(jnp.int32), 0, e - 1
    )
    row = fb[g] + (tt - ts[g])
    row = jnp.where(active, row, mb - 1)
    g = jnp.where(active, g, e - 1)

    prev_row = jnp.concatenate([jnp.full((1,), -1, jnp.int32), row[:-1]])
    prev_g = jnp.concatenate([jnp.full((1,), -1, jnp.int32), g[:-1]])
    nxt_active = jnp.concatenate([active[1:], jnp.zeros((1,), bool)])
    nxt_row = jnp.concatenate([row[1:], jnp.full((1,), -1, jnp.int32)])
    nxt_g = jnp.concatenate([g[1:], jnp.full((1,), -1, jnp.int32)])

    first_row = active & (row != prev_row)
    last_row = active & ((row != nxt_row) | ~nxt_active)
    first_grp = active & (g != prev_g)
    last_grp = active & ((g != nxt_g) | ~nxt_active)

    tiles = jnp.stack([
        row, g,
        first_row.astype(jnp.int32), last_row.astype(jnp.int32),
        first_grp.astype(jnp.int32), last_grp.astype(jnp.int32),
        active.astype(jnp.int32),
    ])
    return tiles, off


def _row_mask(tiles_ref, off_ref, t, bm):
    """(bm, 1) bool — rows of tile t's block that belong to tile t's group."""
    g = tiles_ref[_GRP, t]
    row0 = tiles_ref[_ROW, t] * bm
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    return (
        (rows >= off_ref[g]) & (rows < off_ref[g + 1])
        & (tiles_ref[_ACTIVE, t] == 1)
    )


# --------------------------------------------------------------------------
# forward kernel (also computes dlhs with swapped operands)
# --------------------------------------------------------------------------

def _gmm_kernel(tiles_ref, off_ref, lhs_ref, rhs_ref, out_ref, acc_ref, *,
                bm: int):
    t = pl.program_id(1)
    kb = pl.program_id(2)
    mask = _row_mask(tiles_ref, off_ref, t, bm)
    lhs = jnp.where(mask, lhs_ref[...], jnp.zeros((), lhs_ref.dtype))
    prod = jnp.dot(lhs, rhs_ref[0], preferred_element_type=jnp.float32)

    first = (tiles_ref[_FIRST_ROW, t] == 1) & (kb == 0)
    last = (tiles_ref[_LAST_ROW, t] == 1) & (kb == pl.num_programs(2) - 1)

    @pl.when(first)
    def _init():
        acc_ref[...] = prod

    @pl.when(~first)
    def _accum():
        acc_ref[...] += prod

    @pl.when(last)
    def _emit():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _gmm_call(lhs, rhs, group_sizes, block_m, block_n, block_k, interpret):
    m, k = lhs.shape
    e, _, n = rhs.shape
    tiles, off = _tile_metadata(group_sizes, m, block_m)
    # K innermost: one output row-block's partial products — across its
    # tiles AND K-blocks — are adjacent grid steps for the scratch
    grid = (n // block_n, tiles.shape[1], k // block_k)
    return pl.pallas_call(
        functools.partial(_gmm_kernel, bm=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (block_m, block_k),
                    lambda j, t, kb, tiles, off: (tiles[_ROW, t], kb),
                ),
                pl.BlockSpec(
                    (1, block_k, block_n),
                    lambda j, t, kb, tiles, off: (tiles[_GRP, t], kb, j),
                ),
            ],
            out_specs=pl.BlockSpec(
                (block_m, block_n),
                lambda j, t, kb, tiles, off: (tiles[_ROW, t], j),
            ),
            scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        interpret=interpret,
    )(tiles, off, lhs, rhs)


# --------------------------------------------------------------------------
# backward: per-group weight gradient
# --------------------------------------------------------------------------

def _gmm_drhs_kernel(tiles_ref, off_ref, lhs_ref, dout_ref, drhs_ref,
                     acc_ref, *, bm: int):
    t = pl.program_id(2)
    mask = _row_mask(tiles_ref, off_ref, t, bm)
    lhs = jnp.where(mask, lhs_ref[...], jnp.zeros((), lhs_ref.dtype))
    # (bm, bk)ᵀ @ (bm, bn) → (bk, bn), contracting the row dim
    prod = jax.lax.dot_general(
        lhs, dout_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(tiles_ref[_FIRST_GRP, t] == 1)
    def _init():
        acc_ref[...] = prod

    @pl.when(tiles_ref[_FIRST_GRP, t] == 0)
    def _accum():
        acc_ref[...] += prod

    @pl.when(tiles_ref[_LAST_GRP, t] == 1)
    def _emit():
        drhs_ref[0] = acc_ref[...].astype(drhs_ref.dtype)


def _gmm_drhs_call(lhs, dout, group_sizes, n_groups, block_m, block_n,
                   block_k, interpret, out_dtype):
    m, k = lhs.shape
    n = dout.shape[1]
    tiles, off = _tile_metadata(group_sizes, m, block_m)
    # tiles innermost: one group's row tiles are adjacent per (j, kb), so
    # the (bk, bn) scratch accumulates a full group before emitting
    grid = (n // block_n, k // block_k, tiles.shape[1])
    drhs = pl.pallas_call(
        functools.partial(_gmm_drhs_kernel, bm=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (block_m, block_k),
                    lambda j, kb, t, tiles, off: (tiles[_ROW, t], kb),
                ),
                pl.BlockSpec(
                    (block_m, block_n),
                    lambda j, kb, t, tiles, off: (tiles[_ROW, t], j),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, block_k, block_n),
                lambda j, kb, t, tiles, off: (tiles[_GRP, t], kb, j),
            ),
            scratch_shapes=[pltpu.VMEM((block_k, block_n), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((n_groups, k, n), out_dtype),
        interpret=interpret,
    )(tiles, off, lhs, dout)
    # empty groups own no tiles: their blocks are never written (the tail
    # flush can leave uninitialized memory there) — mask them to zero
    return jnp.where(group_sizes[:, None, None] > 0, drhs, 0).astype(out_dtype)


# --------------------------------------------------------------------------
# custom VJP + public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gmm(lhs, rhs, group_sizes, block_m, block_n, block_k, interpret):
    return _gmm_call(
        lhs, rhs, group_sizes, block_m, block_n, block_k, interpret
    )


def _gmm_fwd(lhs, rhs, group_sizes, block_m, block_n, block_k, interpret):
    out = _gmm_call(
        lhs, rhs, group_sizes, block_m, block_n, block_k, interpret
    )
    return out, (lhs, rhs, group_sizes)


def _gmm_bwd(block_m, block_n, block_k, interpret, res, dout):
    lhs, rhs, group_sizes = res
    k = lhs.shape[1]
    n = rhs.shape[2]
    # dlhs rows of group e: dout rows @ rhs[e]ᵀ — the same grouped matmul
    # with (N', K') = (K, N); blocks re-fit as DIVISORS of the swapped dims
    # (a non-divisor block would silently truncate the grid)
    dlhs = _gmm_call(
        dout, rhs.swapaxes(1, 2), group_sizes,
        block_m, _fit_block_div(block_n, k), _fit_block_div(block_k, n),
        interpret,
    )
    drhs = _gmm_drhs_call(
        lhs, dout, group_sizes, rhs.shape[0], block_m, block_n, block_k,
        interpret, rhs.dtype,
    )
    return dlhs.astype(lhs.dtype), drhs, _int_zeros(group_sizes)


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


def grouped_matmul(
    lhs: jax.Array,
    rhs: jax.Array,
    group_sizes: jax.Array,
    *,
    block_m: int = DEFAULT_BLOCK_M,
    block_n: int = DEFAULT_BLOCK_N,
    block_k: int = DEFAULT_BLOCK_K,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Per-group matmul over contiguous row groups.

    lhs (M, K) with rows sorted so group e occupies rows
    [Σ_{i<e} group_sizes[i], Σ_{i≤e} group_sizes[i]); rhs (E, K, N);
    group_sizes (E,) int32 → out (M, N) in lhs.dtype where group e's rows
    are ``lhs[rows_e] @ rhs[e]``.

    Requirements for the kernel path: ``sum(group_sizes) == M`` (pad the
    final group to cover alignment rows — their outputs are garbage-free
    zeros only if the padded lhs rows are zero), M divisible by block_m,
    N by block_n, and K and N multiples of 128 (lane tiling; the backward
    swaps them). Rows past ``sum(group_sizes)`` are only supported by the
    reference path.

    ``use_pallas=None`` auto-selects the kernel on TPU and the XLA
    reference elsewhere; ``interpret=True`` forces the kernel through the
    Pallas interpreter (CPU-testable). Differentiable in lhs and rhs.
    """
    m, k = lhs.shape
    e, k2, n = rhs.shape
    if k != k2 or group_sizes.shape != (e,):
        raise ValueError(
            f"shape mismatch: lhs {lhs.shape}, rhs {rhs.shape}, "
            f"group_sizes {group_sizes.shape}"
        )
    if use_pallas is None:
        use_pallas = _on_tpu()
    kernel = pltpu is not None and (use_pallas or interpret)
    OPS_TRACED.labels(  # lint: jit-impure-ok — counts traces on purpose
        "grouped_matmul",
        ("pallas" if use_pallas else "interpret") if kernel
        else "reference",
    ).inc()
    if not kernel:
        if _on_tpu():
            # the reference is O(E·M·K·N) — fine for tests, a silent
            # E× throughput tax if it engages on real hardware. Warn
            # once, loudly, naming the actual cause.
            global _warned_tpu_fallback
            if not _warned_tpu_fallback:
                _warned_tpu_fallback = True
                cause = (
                    "jax.experimental.pallas.tpu failed to import — "
                    "the jax install cannot run the kernel"
                    if pltpu is None else
                    "use_pallas=False was passed — leave it unset (or "
                    "True) for the kernel path"
                )
                print(  # lint: jit-impure-ok — one-shot trace-time warning
                    "[grouped_matmul] WARNING: XLA reference fallback on "
                    f"a TPU backend (O(E*M*K*N) flops — every expert "
                    f"multiplies every row): {cause}.",
                    file=sys.stderr, flush=True,
                )
        return grouped_matmul_reference(lhs, rhs, group_sizes)

    block_m = _fit_block(block_m, m)
    block_n = _fit_block(block_n, n)
    if m % block_m or n % block_n:
        raise ValueError(
            f"(M, N) = ({m}, {n}) must be divisible by blocks "
            f"({block_m}, {block_n})"
        )
    if k % 128:
        # lane tiling, and the guarantee that _fit_block_div always finds
        # a divisor for the K grid here and the swapped dims in backward
        raise ValueError(f"K = {k} must be a multiple of 128")
    if n % 128:
        # backward runs the forward kernel with (N', K') = (K, N), so N
        # must satisfy K's constraint too
        raise ValueError(f"N = {n} must be a multiple of 128")
    block_k = _fit_block_div(block_k, k)
    return _gmm(
        lhs, rhs, group_sizes.astype(jnp.int32),
        block_m, block_n, block_k, interpret,
    )
