"""Shared loss primitives for the in-tree model family."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def next_token_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy. logits: (batch, seq, vocab) float32,
    targets: (batch, seq) int32. The single definition used by the dense,
    MoE, and pipelined loss functions."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)
