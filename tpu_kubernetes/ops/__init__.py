"""tpu_kubernetes.ops — part of the in-tree TPU compute stack (being built;
see __graft_entry__.py and bench.py once present)."""
