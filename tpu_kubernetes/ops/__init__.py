"""tpu_kubernetes.ops — TPU kernels and core numerical ops for the in-tree
training stack (flash attention in Pallas; RMSNorm/RoPE as XLA-fused jnp)."""

from tpu_kubernetes.ops.flash_attention import (  # noqa: F401
    attention_reference,
    flash_attention,
)
from tpu_kubernetes.ops.grouped_matmul import (  # noqa: F401
    grouped_matmul,
    grouped_matmul_reference,
)
from tpu_kubernetes.ops.losses import next_token_nll  # noqa: F401
from tpu_kubernetes.ops.norms import (  # noqa: F401
    apply_rope,
    rms_norm,
    rope_frequencies,
)
