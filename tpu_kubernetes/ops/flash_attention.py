"""Causal flash attention as a Pallas TPU kernel (fwd + bwd, custom VJP).

The hot op of the in-tree training stack (the framework's MaxText-analog
example job, SURVEY §2.7). Design follows the TPU flash-attention pattern:

* Online-softmax forward: grid over (batch, heads, q-blocks); K/V live in
  VMEM per (b,h) and are walked block-by-block with a dynamic-bound
  ``fori_loop`` so causal q-blocks stop at the diagonal. Log-sum-exp is saved
  for the backward pass.
* Backward as two kernels: dQ (grid over q-blocks, walking K/V) and dK/dV
  (grid over kv-blocks, walking Q), both recomputing P from the saved LSE —
  O(seq) memory instead of the O(seq²) score matrix.
* All matmuls accumulate in float32 (``preferred_element_type``) and tiles
  are 128-aligned for the MXU.

A plain-XLA reference implementation is kept alongside: it is the
correctness oracle in tests (pallas runs in interpret mode on CPU) and the
fallback on non-TPU backends.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu_kubernetes.obs import REGISTRY

try:  # pltpu only imports on TPU-capable installs; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# trace-time dispatch accounting: the wrapper body runs once per jit
# TRACE and never in the compiled program, so incrementing here is zero
# steady-state overhead — and "which lane got traced, how often" is how
# /metrics reveals a silent reference-path fallback on real hardware
OPS_TRACED = REGISTRY.counter(
    "tpu_ops_traced_total",
    "kernel wrapper traces by op and dispatch lane (counts jit traces, "
    "not executions — wrapper bodies only run at trace time)",
    labelnames=("op", "path"),
)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# TPU block specs need the trailing dims tile-aligned; scalar-per-row
# tensors (lse, delta) therefore carry a small broadcast lane dim.
LSE_LANES = 8


def _vmem_spec(block_shape=None, index_map=None):
    kwargs = {}
    if _VMEM is not None:
        kwargs["memory_space"] = _VMEM
    if block_shape is None:
        return pl.BlockSpec(**kwargs)
    return pl.BlockSpec(block_shape, index_map, **kwargs)


# --------------------------------------------------------------------------
# reference (XLA) implementation — oracle + non-TPU fallback
# --------------------------------------------------------------------------

def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Plain-XLA attention. q,k,v: (batch, heads, seq, head_dim)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool), seq_k - seq_q)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                block_q, block_k, causal, sm_scale, offset):
    # offset = seq_k - seq_q aligns the causal diagonal bottom-right, matching
    # attention_reference for cross-length (e.g. decode) calls
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # (block_q, d)
    seq_k = k_ref.shape[2]
    head_dim = q.shape[-1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    row_ids = offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                    # (block_q, block_k)
        if causal:
            col_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(col_ids <= row_ids, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # q block qi only attends kv blocks up to its (offset-aligned) diagonal
        num_kb = jnp.minimum(
            ((qi + 1) * block_q + offset + block_k - 1) // block_k,
            seq_k // block_k,
        )
    else:
        num_kb = seq_k // block_k
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))

    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse = m + jnp.log(l)                                     # (block_q, 1)
    lse_ref[0, 0] = jnp.broadcast_to(lse, (block_q, LSE_LANES)).astype(
        lse_ref.dtype
    )


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[2]
    grid = (batch, heads, seq_q // block_q)
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k,
            causal=causal, sm_scale=sm_scale, offset=seq_k - seq_q,
        ),
        grid=grid,
        in_specs=[
            _vmem_spec((1, 1, block_q, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, seq_k, head_dim), lambda b, h, i: (b, h, 0, 0)),
            _vmem_spec((1, 1, seq_k, head_dim), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, 1, block_q, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, block_q, LSE_LANES), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_q, LSE_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   block_q, block_k, causal, sm_scale, offset):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                      # (block_q, d)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0][:, None]                       # (block_q, 1)
    delta = delta_ref[0, 0, :, 0][:, None]
    seq_k = k_ref.shape[2]
    head_dim = q.shape[-1]

    row_ids = offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(kb, dq):
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            col_ids = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(col_ids <= row_ids, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (block_q, block_k)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        num_kb = jnp.minimum(
            ((qi + 1) * block_q + offset + block_k - 1) // block_k,
            seq_k // block_k,
        )
    else:
        num_kb = seq_k // block_k
    dq = jax.lax.fori_loop(
        0, num_kb, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q, block_k, causal, sm_scale,
                    offset):
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)                      # (block_k, d)
    v = v_ref[0, 0].astype(jnp.float32)
    seq_q = q_ref.shape[2]
    head_dim = k.shape[-1]

    col_ids = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(qb_rel, carry):
        dk, dv, qb0 = carry
        qb = qb0 + qb_rel
        q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q), 0][:, None]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q), 0][:, None]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            row_ids = offset + qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(col_ids <= row_ids, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (block_q, block_k)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new, qb0

    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    if causal:
        # kv block ki only receives gradient from q rows at/after its first
        # column (offset-aligned)
        qb0 = jnp.maximum(ki * block_k - offset, 0) // block_q
        num_qb = seq_q // block_q - qb0
    else:
        qb0 = jnp.int32(0)
        num_qb = seq_q // block_q
    dk, dv, _ = jax.lax.fori_loop(0, num_qb, body, (zeros, zeros, qb0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(causal, sm_scale, block_q, block_k, interpret, residuals, do):
    q, k, v, o, lse = residuals
    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[2]
    # delta = rowsum(dO * O) — cheap XLA op, fused upstream; broadcast to
    # the lane-aligned layout the kernels read
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )                                                        # (b, h, seq_q, 1)
    delta = jnp.broadcast_to(delta, (*delta.shape[:-1], LSE_LANES))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=block_q, block_k=block_k,
            causal=causal, sm_scale=sm_scale, offset=seq_k - seq_q,
        ),
        grid=(batch, heads, seq_q // block_q),
        in_specs=[
            _vmem_spec((1, 1, block_q, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, seq_k, head_dim), lambda b, h, i: (b, h, 0, 0)),
            _vmem_spec((1, 1, seq_k, head_dim), lambda b, h, i: (b, h, 0, 0)),
            _vmem_spec((1, 1, block_q, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, block_q, LSE_LANES), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, block_q, LSE_LANES), lambda b, h, i: (b, h, i, 0)),
        ],
        out_specs=_vmem_spec(
            (1, 1, block_q, head_dim), lambda b, h, i: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            causal=causal, sm_scale=sm_scale, offset=seq_k - seq_q,
        ),
        grid=(batch, heads, seq_k // block_k),
        in_specs=[
            _vmem_spec((1, 1, seq_q, head_dim), lambda b, h, i: (b, h, 0, 0)),
            _vmem_spec((1, 1, block_k, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, block_k, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, seq_q, head_dim), lambda b, h, i: (b, h, 0, 0)),
            _vmem_spec((1, 1, seq_q, LSE_LANES), lambda b, h, i: (b, h, 0, 0)),
            _vmem_spec((1, 1, seq_q, LSE_LANES), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, 1, block_k, head_dim), lambda b, h, i: (b, h, i, 0)),
            _vmem_spec((1, 1, block_k, head_dim), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom-VJP wrapper + public dispatcher
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, residuals, do):
    return _bwd(causal, sm_scale, block_q, block_k, interpret, residuals, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _fit_block(block: int, seq: int) -> int:
    """Largest halving of ``block`` that divides ``seq`` (seq=768 with
    block=512 → 256), so raising the default block size never breaks
    sequence lengths the smaller default accepted. Degenerate fits
    (< 16 — pathological for the MXU) fall through to the caller's
    divisibility error instead."""
    orig = block = min(block, seq)
    while block >= 16 and seq % block:
        block //= 2
    if block < 16 and block < seq:
        # no halving ≥ the bf16 min sublane tile divides seq (e.g. 1000):
        # hand back the original so the caller's divisibility check raises
        # instead of silently lowering a sub-16 block Pallas can reject
        return orig
    return block


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Causal attention over (batch, heads, seq, head_dim) tensors.

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU, the XLA
    reference elsewhere. ``interpret=True`` forces the kernel through the
    Pallas interpreter (CPU-testable).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = _on_tpu()
    OPS_TRACED.labels(  # lint: jit-impure-ok — counts traces on purpose
        "flash_attention",
        "pallas" if use_pallas else ("interpret" if interpret
                                     else "reference"),
    ).inc()
    if not (use_pallas or interpret):
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)

    seq_q, seq_k = q.shape[2], k.shape[2]
    block_q = _fit_block(block_q, seq_q)
    block_k = _fit_block(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) must be divisible by the "
            f"block sizes ({block_q}, {block_k})"
        )
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
