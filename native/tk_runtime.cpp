// tk_runtime — native runtime layer for the tpu-kubernetes CLI.
//
// The reference framework's runtime is a compiled (Go) binary whose
// execution layer streams a subprocess's output through to the operator
// (reference: shell/run_shell_cmd.go:8-13, run_terraform.go:11-80). This
// is the C++ equivalent for the rebuild: a line-streaming process runner
// with monotonic-deadline timeout enforcement and a tail capture for
// error reporting, plus flock(2)-based advisory locking used by the local
// state backend to make its stale-lock-break critical section atomic on a
// host. Exposed to Python over a minimal C ABI via ctypes
// (tpu_kubernetes/native/__init__.py) — no pybind11 dependency.
//
// Build: make native   (g++ -O2 -shared -fPIC)

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

// Child pgid of the in-flight run (one run at a time per process — the
// executor is sequential). SIGINT/SIGTERM are forwarded to the child's
// process group while a run is active: the child runs in its own pgrp (so
// a deadline kill reaps grandchildren), which takes it out of the
// terminal's foreground group — without forwarding, Ctrl-C could no
// longer interrupt a wedged terraform apply.
volatile pid_t g_child_pgid = 0;

void forward_signal(int sig) {
  const pid_t p = g_child_pgid;
  if (p > 0) kill(-p, sig);
}

double monotonic_now() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + static_cast<double>(ts.tv_nsec) * 1e-9;
}

// Keep the last tail_cap-1 bytes of output for error messages.
void append_tail(char *tail, int tail_cap, int *tail_len, const char *buf,
                 ssize_t n) {
  if (tail == nullptr || tail_cap <= 1) return;
  const int cap = tail_cap - 1;  // reserve NUL
  if (n >= cap) {
    memcpy(tail, buf + (n - cap), cap);
    *tail_len = cap;
  } else if (*tail_len + n <= cap) {
    memcpy(tail + *tail_len, buf, n);
    *tail_len += static_cast<int>(n);
  } else {
    const int keep = cap - static_cast<int>(n);
    memmove(tail, tail + (*tail_len - keep), keep);
    memcpy(tail + keep, buf, n);
    *tail_len = cap;
  }
  tail[*tail_len] = '\0';
}

}  // namespace

extern "C" {

// Exit-code space: >=0 child exit status; TK_ERR_SPAWN spawn failure;
// TK_ERR_TIMEOUT killed on deadline; TK_ERR_SIGNAL child died on a signal;
// TK_ERR_INTERNAL pipe/fork plumbing failure.
enum {
  TK_ERR_SPAWN = -1,
  TK_ERR_TIMEOUT = -2,
  TK_ERR_SIGNAL = -3,
  TK_ERR_INTERNAL = -4,
};

// Run argv (NULL-terminated) in cwd (may be NULL), merging the child's
// stdout+stderr through one pipe. When stream != 0 every chunk is echoed
// to our stdout as it arrives (the operator watches terraform progress
// live). The last bytes are kept in tail/tail_cap for error reporting.
// timeout_s <= 0 means no deadline; on expiry the whole child process
// group gets SIGKILL.
int tk_run_streaming(const char *const argv[], const char *cwd,
                     double timeout_s, int stream, char *tail, int tail_cap) {
  int tail_len = 0;
  if (tail != nullptr && tail_cap > 0) tail[0] = '\0';

  int pipefd[2];
  if (pipe(pipefd) != 0) return TK_ERR_INTERNAL;

  const pid_t pid = fork();
  if (pid < 0) {
    close(pipefd[0]);
    close(pipefd[1]);
    return TK_ERR_INTERNAL;
  }

  if (pid == 0) {  // child
    setpgid(0, 0);  // own process group so a timeout kill reaps grandchildren
    close(pipefd[0]);
    dup2(pipefd[1], STDOUT_FILENO);
    dup2(pipefd[1], STDERR_FILENO);
    close(pipefd[1]);
    if (cwd != nullptr && chdir(cwd) != 0) _exit(127);
    execvp(argv[0], const_cast<char *const *>(argv));
    // exec failed — report over the (now-dup2'd) pipe and die with the
    // shell's command-not-found status
    fprintf(stderr, "tk_runtime: exec %s: %s\n", argv[0], strerror(errno));
    _exit(127);
  }

  // parent: forward terminal signals to the child's process group for the
  // duration of the run (see g_child_pgid above). Both sides setpgid so
  // there is no window where kill(-pgid) targets a group that does not
  // exist yet; EACCES after the child exec'd means the child already did it.
  close(pipefd[1]);
  setpgid(pid, pid);
  g_child_pgid = pid;
  struct sigaction fwd = {}, old_int = {}, old_term = {};
  fwd.sa_handler = forward_signal;
  sigemptyset(&fwd.sa_mask);
  sigaction(SIGINT, &fwd, &old_int);
  sigaction(SIGTERM, &fwd, &old_term);

  const double deadline =
      timeout_s > 0 ? monotonic_now() + timeout_s : 0.0;
  bool timed_out = false;
  bool child_done = false;  // reaped via WNOHANG mid-loop
  int status = 0;
  char buf[8192];

  // Read until EOF, deadline, or the direct child exits (with a short
  // bounded drain). The drain bound matters: a daemonizing grandchild
  // that inherited the merged stdout/stderr fd can hold the pipe open —
  // and keep chattering on it — forever after the child exits; the
  // Python subprocess fallback returns when the child exits, so we must
  // too, no matter what the grandchild does.
  double drain_deadline = 0.0;  // set once the child is reaped
  for (;;) {
    const double now = monotonic_now();
    if (!child_done && waitpid(pid, &status, WNOHANG) == pid) {
      child_done = true;
      drain_deadline = now + 0.2;  // grab already-buffered output, then go
    }
    if (child_done && now >= drain_deadline) break;
    if (!child_done && deadline > 0 && now >= deadline) {
      timed_out = true;
      break;
    }
    int poll_ms = 100;  // bounded so child exit is noticed promptly
    if (child_done)
      poll_ms = static_cast<int>((drain_deadline - now) * 1000.0) + 1;
    else if (deadline > 0) {
      const int left_ms = static_cast<int>((deadline - now) * 1000.0) + 1;
      if (left_ms < poll_ms) poll_ms = left_ms;
    }
    struct pollfd pfd = {pipefd[0], POLLIN, 0};
    const int pr = poll(&pfd, 1, poll_ms);
    if (pr < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (pr == 0) continue;  // tick: re-check child/deadline/drain above
    const ssize_t n = read(pipefd[0], buf, sizeof buf);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    if (n == 0) break;  // EOF — all writers closed their ends
    if (stream) {
      ssize_t off = 0;
      while (off < n) {
        const ssize_t w = write(STDOUT_FILENO, buf + off, n - off);
        if (w <= 0) break;
        off += w;
      }
    }
    append_tail(tail, tail_cap, &tail_len, buf, n);
  }
  close(pipefd[0]);

  if (timed_out) {
    kill(-pid, SIGKILL);  // the whole process group
    if (!child_done) kill(pid, SIGKILL);  // pid is reaped once child_done
  }

  int wait_err = 0;
  for (; !child_done;) {
    if (waitpid(pid, &status, 0) >= 0) break;
    if (errno != EINTR) {
      wait_err = 1;
      break;
    }
  }
  g_child_pgid = 0;
  sigaction(SIGINT, &old_int, nullptr);
  sigaction(SIGTERM, &old_term, nullptr);
  if (wait_err) return TK_ERR_INTERNAL;
  if (timed_out) return TK_ERR_TIMEOUT;
  if (WIFEXITED(status)) {
    const int code = WEXITSTATUS(status);
    // 127 after our own exec error message means spawn failure
    if (code == 127 && tail != nullptr &&
        strstr(tail, "tk_runtime: exec ") != nullptr)
      return TK_ERR_SPAWN;
    return code;
  }
  if (WIFSIGNALED(status)) return TK_ERR_SIGNAL;
  return TK_ERR_INTERNAL;
}

// Acquire an exclusive advisory flock on path, creating it if needed.
// Retries until timeout_ms (0 = single non-blocking attempt; < 0 = wait
// forever). Returns the held fd (>= 0) or -1 on timeout/error. The lock
// dies with the fd — including on process crash, which is exactly the
// property the JSON-lockfile scheme cannot provide by itself.
int tk_lock_acquire(const char *path, int timeout_ms) {
  const int fd = open(path, O_CREAT | O_RDWR | O_CLOEXEC, 0644);
  if (fd < 0) return -1;
  const double deadline =
      timeout_ms >= 0 ? monotonic_now() + timeout_ms / 1000.0 : 0.0;
  for (;;) {
    if (flock(fd, LOCK_EX | LOCK_NB) == 0) return fd;
    if (errno != EWOULDBLOCK && errno != EINTR) break;
    if (timeout_ms >= 0 && monotonic_now() >= deadline) break;
    usleep(20 * 1000);
  }
  close(fd);
  return -1;
}

int tk_lock_release(int fd) {
  if (fd < 0) return -1;
  flock(fd, LOCK_UN);
  return close(fd);
}

// Library self-identification for the ctypes loader's version check.
int tk_abi_version() { return 1; }

}  // extern "C"
